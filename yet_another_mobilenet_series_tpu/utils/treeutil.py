"""Pytree structure utilities shared by NAS rematerialization and the ZeRO
optimizer-state transforms."""

from __future__ import annotations

import jax


def map_params_shaped(obj, params_structure, fn):
    """Recursively applies ``fn`` to every subtree of ``obj`` whose pytree
    structure equals ``params_structure`` (optax states wrap params-shaped
    accumulator trees inside NamedTuples; this finds them without knowing the
    optimizer's composition)."""
    try:
        if jax.tree.structure(obj) == params_structure:
            return fn(obj)
    except Exception:  # yamt-lint: disable=YAMT012 — structure probe: "not params-shaped" is the expected answer, recursion below handles it
        pass
    if isinstance(obj, dict):
        return {k: map_params_shaped(v, params_structure, fn) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # NamedTuple
        return type(obj)(*(map_params_shaped(v, params_structure, fn) for v in obj))
    if isinstance(obj, (tuple, list)):
        return type(obj)(map_params_shaped(v, params_structure, fn) for v in obj)
    return obj
