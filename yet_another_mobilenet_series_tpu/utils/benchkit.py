"""Shared fixture builder for the throughput benchmarks (bench.py and
scripts/bench_bn.py) so the headline recipe — MobileNetV3-L, RMSProp+WD,
exp-decay LR, EMA, bf16, device-resident fake batch — exists in one place.

Also home of the one trustworthy device barrier on this sandbox: see
``sync`` (PROFILE.md "Measurement methodology").
"""

from __future__ import annotations

import jax
import numpy as np


def sync(arr) -> float:
    """Hard sync: device_get of a dependent scalar. ``block_until_ready`` is
    NOT a reliable barrier through the axon tunnel — it often returns at
    dispatch-acknowledge time (round 2 measured a 3.6x-inflated rate that
    way). Only an actual device->host transfer of a value that depends on
    the work is trustworthy here."""
    return float(np.asarray(jax.device_get(arr)).ravel()[0])


def build_train_fixture(
    batch: int,
    image_size: int,
    *,
    remat: bool = False,
    remat_policy: str = "full",
    bn_mode: str = "exact",
    conv1x1_dot: bool = False,
    arch: str = "mobilenet_v3_large",
):
    """Returns (step_fn, replicated_train_state, sharded_batch, net) for the
    headline training recipe at the given global batch, on the full visible
    device mesh."""
    from ..config import config_from_dict
    from ..models import get_model
    from ..parallel import dp, mesh as mesh_lib
    from ..train import optim, schedules, steps

    cfg = config_from_dict({
        "model": {"arch": arch, "dropout": 0.2},
        "optim": {"optimizer": "rmsprop", "weight_decay": 1e-5},
        "schedule": {"schedule": "exp_decay", "base_lr": 0.064, "warmup_epochs": 5.0},
        "ema": {"enable": True},
        "train": {"batch_size": batch, "compute_dtype": "bfloat16",
                  "remat": remat, "remat_policy": remat_policy, "bn_mode": bn_mode,
                  "conv1x1_dot": conv1x1_dot},
    })
    net = get_model(cfg.model, image_size)
    mesh = mesh_lib.make_mesh(len(jax.devices()))
    lr_fn = schedules.make_lr_schedule(cfg.schedule, batch, 1281167 // batch, 350)
    params, _ = net.init(jax.random.PRNGKey(0))
    optimizer = optim.make_optimizer(cfg.optim, lr_fn, params)
    ts = steps.init_train_state(net, cfg, optimizer, jax.random.PRNGKey(0))
    ts = mesh_lib.replicate(ts, mesh)
    step_fn = dp.make_dp_train_step(net, cfg, optimizer, lr_fn, mesh)
    rng = np.random.RandomState(0)
    host_batch = {
        "image": rng.normal(0, 1, (batch, image_size, image_size, 3)).astype(np.float32),
        "label": (np.arange(batch) % 1000).astype(np.int32),
    }
    b = mesh_lib.shard_batch(host_batch, mesh)
    return step_fn, ts, b, net
