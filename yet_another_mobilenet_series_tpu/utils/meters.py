"""Host-side metric aggregation + logging (reference: AverageMeter/accuracy
in utils/common.py, SURVEY.md §2 #13).

Device-side reduction already happened inside the step (pmean/psum in
train/steps.py), so these meters only average across steps on the host.
"""

from __future__ import annotations

import time
from collections import defaultdict


class AverageMeter:
    def __init__(self):
        self.reset()

    def reset(self):
        self.sum = 0.0
        self.count = 0

    def update(self, value: float, n: int = 1):
        self.sum += float(value) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)


class MetricLogger:
    """Accumulates step metrics and renders one log line every N steps,
    including images/sec/chip — the first-class tracked metric
    (BASELINE.json:2).

    Metrics are stored as device arrays and only converted to host floats at
    snapshot time: calling float() per step would block the host on the
    just-dispatched XLA program and kill async dispatch (the device would
    idle while the host preps the next batch)."""

    def __init__(self):
        self._pending: list[dict] = []
        self._t0 = time.perf_counter()
        self._images = 0

    def update(self, metrics: dict, batch_images: int = 0):
        self._pending.append(metrics)
        self._images += batch_images

    def snapshot_and_reset(self, num_chips: int = 1) -> dict:
        meters: dict[str, AverageMeter] = defaultdict(AverageMeter)
        for metrics in self._pending:
            for k, v in metrics.items():
                meters[k].update(float(v))  # blocks here, once per log window
        dt = time.perf_counter() - self._t0
        out = {k: m.avg for k, m in meters.items()}
        if self._images:
            out["images_per_sec"] = self._images / dt
            out["images_per_sec_per_chip"] = self._images / dt / max(num_chips, 1)
        self._pending.clear()
        self._t0 = time.perf_counter()
        self._images = 0
        return out


def format_metrics(prefix: str, metrics: dict) -> str:
    parts = [prefix]
    for k, v in sorted(metrics.items()):
        parts.append(f"{k}={v:.4g}")
    return " ".join(parts)
