"""Analytic FLOPs/params profiler (reference: utils/model_profiling.py,
SURVEY.md §2 #10).

The reference attaches forward hooks to count per-module n_macs/n_params; in
JAX the model is a static spec tree, so we compute the same numbers
analytically — exactly, with no tracing — including the **per-atom FLOPs cost
table** that weights the AtomNAS BN-gamma L1 penalty (SURVEY.md §3.2).

Conventions match the common MobileNet accounting (and the reference's
profiler): MACs counted for convs and fully-connected layers only; BN and
activations are free; params count all trainables incl. BN gamma/beta but not
running stats.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..models.specs import Network
from ..ops.blocks import ConvBNAct, InvertedResidual


def _conv_out(hw: int, k: int, stride: int) -> int:
    # symmetric padding k//2 (see ops/layers.py): out = floor((h-1)/s)+1
    return (hw - 1) // stride + 1


@dataclass(frozen=True)
class LayerProfile:
    name: str
    macs: int
    params: int
    out_hw: int
    out_channels: int


@dataclass(frozen=True)
class ModelProfile:
    layers: tuple[LayerProfile, ...]
    # per-block cost vector: macs attributable to each expanded channel
    # ("atom") of every InvertedResidual block, keyed by block index.
    atom_costs: dict[int, np.ndarray]

    @property
    def total_macs(self) -> int:
        return sum(l.macs for l in self.layers)

    @property
    def total_params(self) -> int:
        return sum(l.params for l in self.layers)

    def summary(self) -> str:
        lines = [f"{'layer':<16}{'out':>10}{'ch':>6}{'MACs':>14}{'params':>12}"]
        for l in self.layers:
            lines.append(f"{l.name:<16}{l.out_hw:>10}{l.out_channels:>6}{l.macs:>14,}{l.params:>12,}")
        lines.append(f"{'TOTAL':<32}{self.total_macs:>14,}{self.total_params:>12,}")
        return "\n".join(lines)


def _profile_conv_bn_act(spec: ConvBNAct, hw: int) -> tuple[int, int, int]:
    out_hw = _conv_out(hw, spec.kernel_size, spec.stride)
    macs = out_hw * out_hw * spec.kernel_size**2 * (spec.in_channels // spec.groups) * spec.out_channels
    params = spec.kernel_size**2 * (spec.in_channels // spec.groups) * spec.out_channels + 2 * spec.out_channels
    return macs, params, out_hw


def _profile_block(spec: InvertedResidual, hw: int) -> tuple[int, int, int, np.ndarray]:
    """Returns (macs, params, out_hw, per-atom cost vector)."""
    e = spec.expanded_channels
    out_hw = _conv_out(hw, 1, spec.stride)
    cost = np.zeros(e, dtype=np.float64)
    macs = 0
    params = 0
    if spec.has_expand:
        # 1x1 expand at input resolution: each expanded channel costs hw^2*cin
        macs += hw * hw * spec.in_channels * e
        params += spec.in_channels * e + 2 * e
        cost += hw * hw * spec.in_channels
    # depthwise branches at output resolution
    off = 0
    for k, g in zip(spec.kernel_sizes, spec.group_channels):
        macs += out_hw * out_hw * k * k * g
        params += k * k * g
        cost[off : off + g] += out_hw * out_hw * k * k
        off += g
    params += 2 * e  # dw BN
    if spec.se_channels:
        se = spec.se_channels
        macs += e * se + se * e
        params += e * se + se + se * e + e
        cost += 2 * se  # one reduce row + one expand column per atom
    # 1x1 project at output resolution
    macs += out_hw * out_hw * e * spec.out_channels
    params += e * spec.out_channels + 2 * spec.out_channels
    cost += out_hw * out_hw * spec.out_channels
    return macs, params, out_hw, cost


def profile_network(net: Network, image_size: int | None = None) -> ModelProfile:
    hw = image_size or net.image_size
    layers: list[LayerProfile] = []
    atom_costs: dict[int, np.ndarray] = {}

    macs, params, hw = _profile_conv_bn_act(net.stem, hw)
    layers.append(LayerProfile("stem", macs, params, hw, net.stem.out_channels))

    for i, blk in enumerate(net.blocks):
        macs, params, hw, cost = _profile_block(blk, hw)
        layers.append(LayerProfile(f"block{i}", macs, params, hw, blk.out_channels))
        atom_costs[i] = cost

    if net.head is not None:
        macs, params, hw = _profile_conv_bn_act(net.head, hw)
        layers.append(LayerProfile("head", macs, params, hw, net.head.out_channels))

    if net.feature is not None:
        f = net.feature
        layers.append(LayerProfile("feature", f.in_features * f.out_features, f.in_features * f.out_features + f.out_features, 1, f.out_features))

    c = net.classifier
    layers.append(LayerProfile("classifier", c.in_features * c.out_features, c.in_features * c.out_features + c.out_features, 1, c.out_features))
    return ModelProfile(tuple(layers), atom_costs)


def masked_macs(net: Network, masks: dict[int, np.ndarray], image_size: int | None = None) -> float:
    """Effective MACs of the supernet under channel masks — the 'remaining
    FLOPs' number the AtomNAS shrink loop logs (SURVEY.md §3.2). Exact for
    atom removal (expand/dw/SE/project terms all scale per-channel)."""
    prof = profile_network(net, image_size)
    total = float(prof.total_macs)
    for i, cost in prof.atom_costs.items():
        m = masks.get(i)
        if m is not None:
            dead = 1.0 - np.asarray(m, dtype=np.float64)
            total -= float(np.dot(cost, dead))
    return total
