"""Structured stdout + TensorBoard logging on the coordinator only
(reference: master-only logging + TB scalars, SURVEY.md §5 observability)."""

from __future__ import annotations

import sys
import time


class Logger:
    def __init__(self, log_dir: str | None = None, enabled: bool = True, tensorboard: bool = False):
        self.enabled = enabled
        self._tb = None
        self._jsonl = None
        self._jsonl_path = None
        self._append = True
        if enabled and log_dir:
            import os

            os.makedirs(log_dir, exist_ok=True)
            # metrics.jsonl is opened lazily at the first scalars() write so
            # mark_fresh_run() — callable only after the checkpoint-restore
            # decision — can truncate it and keep step rows monotonic
            self._jsonl_path = os.path.join(log_dir, "metrics.jsonl")
            if tensorboard:
                import tensorflow as tf

                self._tb = tf.summary.create_file_writer(log_dir)

    def mark_fresh_run(self):
        """No checkpoint was restored: truncate the metrics stream instead of
        appending behind a previous run's rows."""
        self._append = False

    def log(self, msg: str):
        if self.enabled:
            ts = time.strftime("%H:%M:%S")
            print(f"[{ts}] {msg}", flush=True)

    def scalars(self, step: int, metrics: dict, prefix: str = ""):
        if self._jsonl is None and self._jsonl_path is not None:
            self._jsonl = open(self._jsonl_path, "a" if self._append else "w")
            self._jsonl_path = None
        if self._jsonl is not None:
            import json

            row = {"step": int(step)}
            row.update({f"{prefix}{k}": float(v) for k, v in metrics.items()})
            self._jsonl.write(json.dumps(row) + "\n")
            self._jsonl.flush()
        if self._tb is None:
            return
        import tensorflow as tf

        with self._tb.as_default():
            for k, v in metrics.items():
                tf.summary.scalar(f"{prefix}{k}", float(v), step=step)

    def error(self, msg: str):
        print(f"ERROR: {msg}", file=sys.stderr, flush=True)

    def close(self):
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None
