"""Structured stdout + jsonl + TensorBoard logging on the coordinator only
(reference: master-only logging + TB scalars, SURVEY.md §5 observability).

This module is THE sanctioned print surface of the package (yamt-lint
YAMT007): everything else routes messages through a :class:`Logger` or the
module-level :func:`emit` — so "the run went quiet" always means the run
went quiet, not that a warning raced past on a worker's stdout.

TensorBoard is best-effort: TPU hosts run TF for tf.data, but lean eval
boxes and CI images may not ship it — a missing/broken tensorflow degrades
to jsonl-only with a single warning instead of crashing the run
(cli/train.py enables tensorboard for every run with a log dir).
"""

from __future__ import annotations

import sys
import time

# the active coordinator Logger, so code without a Logger handle (the data
# pipeline's host warnings) can still route through one via emit()
_CURRENT: "Logger | None" = None
_TB_WARNED = False


def emit(msg: str) -> None:
    """Route a message through the active coordinator Logger when one
    exists; plain stdout otherwise (workers, bare library use)."""
    if _CURRENT is not None and _CURRENT.enabled:
        _CURRENT.log(msg)
    else:
        print(msg, flush=True)


class Logger:
    def __init__(self, log_dir: str | None = None, enabled: bool = True, tensorboard: bool = False):
        self.enabled = enabled
        self._tb = None
        self._jsonl = None
        self._jsonl_path = None
        self._append = True
        self._registry = None
        if enabled and log_dir:
            import os

            os.makedirs(log_dir, exist_ok=True)
            # metrics.jsonl is opened lazily at the first scalars() write so
            # mark_fresh_run() — callable only after the checkpoint-restore
            # decision — can truncate it and keep step rows monotonic
            self._jsonl_path = os.path.join(log_dir, "metrics.jsonl")
            if tensorboard:
                try:
                    import tensorflow as tf
                except Exception as e:  # TF missing or broken: degrade, once
                    global _TB_WARNED
                    if not _TB_WARNED:
                        _TB_WARNED = True
                        print(
                            "WARNING: tensorboard logging disabled "
                            f"(tensorflow import failed: {type(e).__name__}: {e}); "
                            "metrics continue in metrics.jsonl",
                            flush=True,
                        )
                else:
                    self._tb = tf.summary.create_file_writer(log_dir)
        if enabled:
            global _CURRENT
            _CURRENT = self

    def set_registry(self, registry) -> None:
        """Attach an obs.MetricsRegistry: every scalars() row carries its
        snapshot under an ``obs/`` prefix — counters, gauges, histogram
        summaries all land in the same metrics.jsonl/TensorBoard stream."""
        self._registry = registry

    def mark_fresh_run(self):
        """No checkpoint was restored: truncate the metrics stream instead of
        appending behind a previous run's rows."""
        self._append = False

    def log(self, msg: str):
        if self.enabled:
            ts = time.strftime("%H:%M:%S")
            print(f"[{ts}] {msg}", flush=True)

    def scalars(self, step: int, metrics: dict, prefix: str = ""):
        row = {f"{prefix}{k}": float(v) for k, v in metrics.items()}
        if self._registry is not None:
            row.update({f"obs/{k}": float(v) for k, v in self._registry.snapshot().items()})
        if self._jsonl is None and self._jsonl_path is not None:
            self._jsonl = open(self._jsonl_path, "a" if self._append else "w")
            self._jsonl_path = None
        if self._jsonl is not None:
            import json

            self._jsonl.write(json.dumps({"step": int(step), **row}) + "\n")
            self._jsonl.flush()
        if self._tb is None:
            return
        import tensorflow as tf

        with self._tb.as_default():
            for k, v in row.items():
                tf.summary.scalar(k, v, step=step)

    def error(self, msg: str):
        print(f"ERROR: {msg}", file=sys.stderr, flush=True)

    def close(self):
        global _CURRENT
        if _CURRENT is self:
            _CURRENT = None
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None
