"""Structured stdout + TensorBoard logging on the coordinator only
(reference: master-only logging + TB scalars, SURVEY.md §5 observability)."""

from __future__ import annotations

import sys
import time


class Logger:
    def __init__(self, log_dir: str | None = None, enabled: bool = True, tensorboard: bool = False):
        self.enabled = enabled
        self._tb = None
        if enabled and tensorboard and log_dir:
            import tensorflow as tf

            self._tb = tf.summary.create_file_writer(log_dir)

    def log(self, msg: str):
        if self.enabled:
            ts = time.strftime("%H:%M:%S")
            print(f"[{ts}] {msg}", flush=True)

    def scalars(self, step: int, metrics: dict, prefix: str = ""):
        if self._tb is None:
            return
        import tensorflow as tf

        with self._tb.as_default():
            for k, v in metrics.items():
                tf.summary.scalar(f"{prefix}{k}", float(v), step=step)

    def error(self, msg: str):
        print(f"ERROR: {msg}", file=sys.stderr, flush=True)
