"""Integer-step periodic triggers for eval/checkpoint/remat cadences.

Replaces fractional-epoch float modulo tests (``epoch % every < 1e-6``), which
silently skip or double-fire events when ``steps_per_epoch`` rounding makes
the accumulated epoch drift past a boundary (VERDICT round-1 weak #2). Step
counts are exact integers, so every boundary fires exactly once regardless of
fractional epoch chunks or resume points.
"""

from __future__ import annotations


class StepCadence:
    """Fires once whenever the step counter crosses a multiple of
    ``every_epochs * steps_per_epoch`` (rounded to ≥1 step when enabled).

    ``due(step)`` is level-triggered per boundary: it returns True at most
    once per crossed boundary, and a single call that jumped several
    boundaries (e.g. cadence finer than the check granularity) fires once.
    ``start_step`` anchors resume: boundaries at or before it are considered
    already fired.
    """

    def __init__(self, every_epochs: float, steps_per_epoch: int, start_step: int = 0):
        if every_epochs and every_epochs > 0:
            self.every = max(int(round(every_epochs * steps_per_epoch)), 1)
            self._next = ((start_step // self.every) + 1) * self.every
        else:
            self.every = 0
            self._next = 0

    def due(self, step: int) -> bool:
        if not self.every or step < self._next:
            return False
        while self._next <= step:
            self._next += self.every
        return True
