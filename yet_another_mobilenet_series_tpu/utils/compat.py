"""Version-portability shims for the jax surface this package touches.

The public home of ``shard_map`` has moved across jax releases:

- jax <= 0.5: ``jax.experimental.shard_map.shard_map``, replication-check
  kwarg spelled ``check_rep``;
- newer jax: top-level ``jax.shard_map``, the kwarg renamed ``check_vma``.

Every production call site in this package imports ``shard_map`` from HERE
and uses the modern ``check_vma`` spelling; the shim resolves the import
across versions and maps ``check_vma`` onto ``check_rep`` when running on the
older API. Importing shard_map from jax directly is exactly the
version-fragile import that broke the seed's tier-1 collection under jax
0.4.37 — yamt-lint rule YAMT006 (analysis/rules_imports.py) now flags it.
"""

from __future__ import annotations

import functools

try:  # newer jax: public top-level export, `check_vma` kwarg
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _CHECK_KW = "check_vma"
except ImportError:  # jax <= 0.5: experimental home, `check_rep` kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


@functools.wraps(_shard_map)
def shard_map(f, **kwargs):
    """``jax.shard_map`` with the modern keyword surface on every jax.

    Accepts ``check_vma`` regardless of version (translated to ``check_rep``
    for old jax). Positional-only ``f`` keeps both underlying signatures
    happy; everything else must be passed by keyword, which every call site
    in this package already does.
    """
    if "check_vma" in kwargs and _CHECK_KW != "check_vma":
        kwargs[_CHECK_KW] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)
