"""Composite blocks: Conv-BN-act, squeeze-excite, inverted residual.

Reference behavior being rebuilt (SURVEY.md §2 #3, §3.4): the MobileNet block
grammar, including the AtomNAS fine-grained inverted residual where the
expanded channels are split into parallel per-kernel-size depthwise branches
("atoms"), whose post-depthwise BatchNorm scales are the prune handles.

TPU-first choices:
- One shared 1x1 expand conv and one shared 1x1 project conv per block (big
  MXU matmuls); only the cheap depthwise convs are per-branch.
- The per-branch BNs of the reference collapse into a single per-channel BN
  over the concatenated branches (mathematically identical — BN is
  channel-wise) so the whole expanded space has one ``gamma`` prune handle.
- Channel pruning is a multiplicative ``mask`` over expanded channels applied
  after the depthwise BN+act. Because every downstream consumer (SE reduce,
  project conv) is linear in those channels, masking is exactly equivalent to
  physically removing them (tested in tests/test_nas.py) — this is how the
  reference's eager "rebuild the net with smaller tensors" becomes an
  XLA-static-shape program (SURVEY.md §3.2, §7 hard part 1).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .activations import get_activation
from .layers import Array, BatchNorm, Conv2D, Dense, global_avg_pool


@dataclass(frozen=True)
class ConvBNAct:
    in_channels: int
    out_channels: int
    kernel_size: int = 3
    stride: int = 1
    groups: int = 1
    active_fn: str = "relu6"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5

    def __post_init__(self):
        get_activation(self.active_fn)  # fail at spec-build time, not in jit

    @property
    def conv(self) -> Conv2D:
        return Conv2D(self.in_channels, self.out_channels, self.kernel_size, self.stride, self.groups)

    @property
    def bn(self) -> BatchNorm:
        return BatchNorm(self.out_channels, self.bn_momentum, self.bn_eps)

    def init(self, key):
        params = {"conv": self.conv.init(key)}
        bn_p, bn_s = self.bn.init()
        params["bn"] = bn_p
        return params, {"bn": bn_s}

    def apply(self, params, state, x, *, train, axis_name=None, compute_dtype=jnp.float32, bn_mode="exact",
              conv1x1_dot=False):
        y = self.conv.apply(params["conv"], x, compute_dtype=compute_dtype, as_dot=conv1x1_dot)
        y, bn_s = self.bn.apply(params["bn"], state["bn"], y, train=train, axis_name=axis_name, mode=bn_mode)
        y = get_activation(self.active_fn)(y)
        return y, {"bn": bn_s}


@dataclass(frozen=True)
class SqueezeExcite:
    """SE over NHWC features: squeeze (global mean) -> reduce FC -> act ->
    expand FC -> gate. ``gate_fn`` is h-sigmoid for MobileNetV3-style nets and
    sigmoid for MNASNet-style (SURVEY.md §2 #3)."""

    channels: int
    se_channels: int
    inner_act: str = "relu"
    gate_fn: str = "hsigmoid"

    def init(self, key):
        k1, k2 = jax.random.split(key)
        # torch Conv2d-default init for the SE FCs: kaiming_uniform(a=sqrt(5))
        # over fan_in, i.e. U(-1/sqrt(fan_in), 1/sqrt(fan_in)).
        def ku(key, fan_in, shape):
            bound = 1.0 / math.sqrt(fan_in)
            return jax.random.uniform(key, shape, jnp.float32, -bound, bound)

        return {
            "reduce": {"w": ku(k1, self.channels, (self.channels, self.se_channels)), "b": jnp.zeros((self.se_channels,), jnp.float32)},
            "expand": {"w": ku(k2, self.se_channels, (self.se_channels, self.channels)), "b": jnp.zeros((self.channels,), jnp.float32)},
        }

    def apply(self, params, x, *, compute_dtype=jnp.float32):
        # Squeeze/gate in float32: tiny FLOPs, and bf16 pooled moments cost
        # accuracy in the gate.
        s = global_avg_pool(x).astype(jnp.float32)  # (N, C)
        s = s @ params["reduce"]["w"] + params["reduce"]["b"]
        s = get_activation(self.inner_act)(s)
        s = s @ params["expand"]["w"] + params["expand"]["b"]
        gate = get_activation(self.gate_fn)(s).astype(x.dtype)
        return x * gate[:, None, None, :]


@dataclass(frozen=True)
class InvertedResidual:
    """MBConv / AtomNAS block.

    ``group_channels[i]`` expanded channels go through a depthwise conv of
    size ``kernel_sizes[i]``; a standard MBConv is the single-kernel case.
    ``sum(group_channels)`` is the expanded width. Residual iff stride==1 and
    in_channels==out_channels (reference semantics, SURVEY.md §3.4).
    """

    in_channels: int
    out_channels: int
    expanded_channels: int
    stride: int = 1
    kernel_sizes: tuple[int, ...] = (3,)
    group_channels: tuple[int, ...] = ()  # defaults to all channels on kernel_sizes[0]
    active_fn: str = "relu6"
    se_channels: int = 0  # 0 = no SE
    se_gate_fn: str = "hsigmoid"
    se_inner_act: str = "relu"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    # 'identity' = linear bottleneck (MBConv). MobileNetV1's depthwise-
    # separable block is this spec with expanded==in and a ReLU here.
    project_act: str = "identity"
    # V1/MNASNet-sepconv blocks never add a residual even when shapes allow.
    allow_residual: bool = True
    # Keep the 1x1 expand conv even when expanded==in (a pruned supernet
    # block can shrink to exactly in_channels; its expand conv must survive).
    force_expand: bool = False
    # Stochastic depth / drop-connect (arXiv:1603.09382; EfficientNet
    # arXiv:1905.11946): per-SAMPLE Bernoulli drop of the residual branch at
    # train time, inverse-scaled by the keep probability so eval needs no
    # rescale. Only meaningful on residual blocks; 0 = off (all non-
    # EfficientNet archs). In-jit: one (N,1,1,1) bernoulli, XLA fuses it.
    drop_path: float = 0.0

    def __post_init__(self):
        for name in (self.active_fn, self.project_act, self.se_gate_fn, self.se_inner_act):
            get_activation(name)  # fail at spec-build time, not in jit
        if not 0.0 <= self.drop_path < 1.0:
            # keep_prob <= 0 would inverse-scale by 1/0 -> NaN from step 0
            raise ValueError(f"drop_path must be in [0, 1), got {self.drop_path}")
        groups = self.group_channels or (self.expanded_channels,)
        object.__setattr__(self, "group_channels", tuple(groups))
        if len(self.group_channels) != len(self.kernel_sizes):
            raise ValueError(f"group_channels {self.group_channels} vs kernel_sizes {self.kernel_sizes}")
        if sum(self.group_channels) != self.expanded_channels:
            raise ValueError(f"group_channels {self.group_channels} must sum to expanded={self.expanded_channels}")
        if any(g <= 0 for g in self.group_channels):
            raise ValueError(f"empty atomic group in {self.group_channels}")

    # -- derived static structure ------------------------------------------
    @property
    def has_expand(self) -> bool:
        return self.force_expand or self.expanded_channels != self.in_channels

    @property
    def has_residual(self) -> bool:
        return self.allow_residual and self.stride == 1 and self.in_channels == self.out_channels

    def _bn(self, c):
        return BatchNorm(c, self.bn_momentum, self.bn_eps)

    def _branches(self):
        """Yields (branch_index, kernel_size, group_channels, offset) —
        single source of truth for the expanded-channel layout used by both
        the XLA and fused-kernel paths."""
        offset = 0
        for i, (k, g) in enumerate(zip(self.kernel_sizes, self.group_channels)):
            yield i, k, g, offset
            offset += g

    def init(self, key):
        keys = jax.random.split(key, 3 + len(self.kernel_sizes))
        params, state = {}, {}
        if self.has_expand:
            params["expand"] = Conv2D(self.in_channels, self.expanded_channels, 1).init(keys[0])
            params["expand_bn"], state["expand_bn"] = self._bn(self.expanded_channels).init()
        for i, (k, g) in enumerate(zip(self.kernel_sizes, self.group_channels)):
            params[f"dw{i}_k{k}"] = Conv2D(g, g, k, self.stride, groups=g).init(keys[1 + i])
        # Single concatenated BN over all branches; its gamma is the per-atom
        # prune handle (SURVEY.md §3.2).
        params["dw_bn"], state["dw_bn"] = self._bn(self.expanded_channels).init()
        if self.se_channels:
            params["se"] = SqueezeExcite(
                self.expanded_channels, self.se_channels, self.se_inner_act, self.se_gate_fn
            ).init(keys[-2])
        params["project"] = Conv2D(self.expanded_channels, self.out_channels, 1).init(keys[-1])
        params["project_bn"], state["project_bn"] = self._bn(self.out_channels).init()
        return params, state

    def apply(
        self,
        params,
        state,
        x,
        *,
        train: bool,
        axis_name: str | None = None,
        compute_dtype=jnp.float32,
        mask: Array | None = None,
        bn_mode: str = "exact",
        conv1x1_dot: bool = False,
        rng: Array | None = None,
    ):
        """mask: optional (expanded_channels,) multiplier zeroing dead atoms.

        The depthwise chain is deliberately the plain XLA lowering: a Pallas
        fused dw+BN+act+mask eval kernel was built and A/B-measured on a real
        v5e in round 2 and lost 10x end-to-end (ops/pallas_kernels.py keeps
        the kernel + the numbers; PROFILE.md has the full verdict)."""
        act = get_activation(self.active_fn)
        new_state = {}
        h = x
        if self.has_expand:
            h = Conv2D(self.in_channels, self.expanded_channels, 1).apply(
                params["expand"], h, compute_dtype=compute_dtype, as_dot=conv1x1_dot
            )
            h, new_state["expand_bn"] = self._bn(self.expanded_channels).apply(
                params["expand_bn"], state["expand_bn"], h, train=train, axis_name=axis_name, mode=bn_mode
            )
            h = act(h)
        branches = []
        for i, k, g, offset in self._branches():
            sl = h[..., offset : offset + g]
            branches.append(
                Conv2D(g, g, k, self.stride, groups=g).apply(params[f"dw{i}_k{k}"], sl, compute_dtype=compute_dtype)
            )
        h = branches[0] if len(branches) == 1 else jnp.concatenate(branches, axis=-1)
        h, new_state["dw_bn"] = self._bn(self.expanded_channels).apply(
            params["dw_bn"], state["dw_bn"], h, train=train, axis_name=axis_name, mode=bn_mode
        )
        h = act(h)
        if mask is not None:
            h = h * mask.astype(h.dtype)
        if self.se_channels:
            h = SqueezeExcite(self.expanded_channels, self.se_channels, self.se_inner_act, self.se_gate_fn).apply(
                params["se"], h, compute_dtype=compute_dtype
            )
        h = Conv2D(self.expanded_channels, self.out_channels, 1).apply(
            params["project"], h, compute_dtype=compute_dtype, as_dot=conv1x1_dot
        )
        h, new_state["project_bn"] = self._bn(self.out_channels).apply(
            params["project_bn"], state["project_bn"], h, train=train, axis_name=axis_name, mode=bn_mode
        )
        h = get_activation(self.project_act)(h)
        if self.has_residual:
            if train and self.drop_path > 0 and rng is not None:
                keep_prob = 1.0 - self.drop_path
                keep = jax.random.bernoulli(rng, keep_prob, (h.shape[0], 1, 1, 1))
                h = h * (keep.astype(h.dtype) / jnp.asarray(keep_prob, h.dtype))
            if mask is not None:
                # A fully-masked block must equal identity exactly — without
                # this gate the project BN's shift (beta - mean*scale) leaks
                # through zeroed inputs, and rematerialization (which drops
                # dead residual blocks, nas/rematerialize.py) would not be
                # equivalent to masking.
                any_alive = (jnp.max(mask) > 0).astype(h.dtype)
                h = h * any_alive
            h = h + x.astype(h.dtype)
        return h, new_state
