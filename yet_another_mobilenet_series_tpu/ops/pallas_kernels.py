"""Pallas TPU kernels for the depthwise hot path.

Depthwise convolution is the one MobileNet op that cannot use the MXU (no
contraction dimension: it is C independent k x k stencils), so it runs on
the VPU and is HBM-bandwidth-bound. The XLA lowering materializes the conv
output, then the BatchNorm affine, then the activation, then the AtomNAS
mask — up to four HBM round trips over the widest tensors in the network.
``fused_depthwise_inference`` does all of it in one VMEM residency:

    y = act((dw_conv(x, w)) * scale + shift) * mask

with the BN folded into per-channel scale/shift (eval semantics — training
BN needs batch stats of the conv output, which requires a second pass; the
train path keeps the XLA lowering, which the compiler already fuses well).

A ``jax.custom_vjp`` wrapper makes the fused forward safe to drop into
differentiated code: the backward pass recomputes with the reference XLA
ops (correctness over speed — profiling on real hardware decides whether a
hand-written backward is worth it; SURVEY.md §2 native table says "Pallas
kernel only if profiling shows a gap", and the gap could not be measured
this round — the sandbox TPU died mid-session).

Everything is validated against the ``ops.layers`` reference in Pallas
interpret mode (tests/test_pallas.py), so the kernels are exercised on CPU
and compile-ready for TPU.

Status: OPT-IN — wired into InvertedResidual.apply(fused_eval=True) and
reachable via cfg.model.fused_eval_kernels on the eval step, default OFF;
flip the default once real-hardware profiling confirms the win. Off-TPU the
blocks fall back to the XLA path unless YAMT_PALLAS_INTERPRET=1 (tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .activations import get_activation


def _dw_kernel(x_ref, w_ref, scale_ref, shift_ref, mask_ref, o_ref, *, k: int, stride: int, act: str, out_h: int, out_w: int):
    """One image per grid step: x_ref is the pre-padded (H+2p, W+2p, C)
    input; the k*k taps are static Python loops (fully unrolled VPU
    multiply-accumulates over strided slices)."""
    x = x_ref[0]  # (H+2p, W+2p, C): drop the size-1 N-block axis
    acc = None
    for i in range(k):
        for j in range(k):
            # strided window of the padded input aligned to output (h, w)
            sl = x[i : i + out_h * stride : stride, j : j + out_w * stride : stride, :]
            term = sl * w_ref[i, j, :]
            acc = term if acc is None else acc + term
    y = acc * scale_ref[...] + shift_ref[...]
    y = get_activation(act)(y)
    o_ref[0] = (y * mask_ref[...]).astype(o_ref.dtype)


# Channel tile: depthwise is channel-independent, so the channel axis blocks
# freely for ANY stride (no halo logic needed, unlike spatial tiling). 128 =
# one VPU lane register width; it bounds per-step VMEM at the widest blocks
# (112x112 spatial x 128ch f32 in+out ~ 13 MB < ~16 MB VMEM; bf16 half that)
# where the old one-image-per-step layout overflowed at real widths.
_C_BLOCK = 128


@functools.partial(jax.jit, static_argnames=("stride", "act", "interpret"))
def _fused_dw_fwd(x, w, scale, shift, mask, *, stride: int, act: str, interpret: bool = False):
    n, h, wd, c = x.shape
    k = w.shape[0]
    pad = k // 2
    out_h = (h - 1) // stride + 1
    out_w = (wd - 1) // stride + 1
    xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))

    cb = min(c, _C_BLOCK)
    kernel = functools.partial(_dw_kernel, k=k, stride=stride, act=act, out_h=out_h, out_w=out_w)
    return pl.pallas_call(
        kernel,
        grid=(n, pl.cdiv(c, cb)),
        in_specs=[
            pl.BlockSpec((1, h + 2 * pad, wd + 2 * pad, cb), lambda i, j: (i, 0, 0, j)),
            pl.BlockSpec((k, k, cb), lambda i, j: (0, 0, j)),
            pl.BlockSpec((cb,), lambda i, j: (j,)),
            pl.BlockSpec((cb,), lambda i, j: (j,)),
            pl.BlockSpec((cb,), lambda i, j: (j,)),
        ],
        out_specs=pl.BlockSpec((1, out_h, out_w, cb), lambda i, j: (i, 0, 0, j)),
        out_shape=jax.ShapeDtypeStruct((n, out_h, out_w, c), x.dtype),
        interpret=interpret,
    )(xp, w, scale, shift, mask)


def _reference_fwd(x, w, scale, shift, mask, *, stride: int, act: str):
    """The XLA lowering the kernel replaces (also the VJP recompute path)."""
    from jax import lax

    k = w.shape[0]
    pad = k // 2
    c = x.shape[-1]
    y = lax.conv_general_dilated(
        x.astype(jnp.float32),
        w[:, :, None, :].astype(jnp.float32),  # (k,k,1,C) HWIO depthwise
        window_strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )
    y = y * scale + shift
    y = get_activation(act)(y)
    return (y * mask).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def fused_depthwise_inference(x, w, scale, shift, mask, stride: int = 1, act: str = "relu6", interpret: bool = False):
    """Fused dw-conv + folded-BN + activation + mask.

    Args:
      x: (N,H,W,C); w: (k,k,C) depthwise taps; scale/shift: (C,) folded BN
      (scale = gamma*rsqrt(var+eps), shift = beta - mean*scale);
      mask: (C,) AtomNAS atom mask (ones when unused).
      interpret: run the Pallas interpreter (CPU testing).
    """
    return _fused_dw_fwd(x, w, scale, shift, mask, stride=stride, act=act, interpret=interpret)


def _vjp_fwd(x, w, scale, shift, mask, stride, act, interpret):
    y = _fused_dw_fwd(x, w, scale, shift, mask, stride=stride, act=act, interpret=interpret)
    return y, (x, w, scale, shift, mask)


def _vjp_bwd(stride, act, interpret, res, g):
    x, w, scale, shift, mask = res
    # correctness-first backward: differentiate the reference lowering
    _, vjp = jax.vjp(lambda *a: _reference_fwd(*a, stride=stride, act=act), x, w, scale, shift, mask)
    return vjp(g)


fused_depthwise_inference.defvjp(_vjp_fwd, _vjp_bwd)


def fold_bn(gamma, beta, mean, var, eps: float = 1e-5):
    """BN eval affine folded to (scale, shift) for the fused kernel."""
    scale = gamma * jax.lax.rsqrt(var + eps)
    return scale, beta - mean * scale
