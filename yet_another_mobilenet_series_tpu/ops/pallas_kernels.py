"""Pallas TPU kernels for the depthwise hot path.

Depthwise convolution is the one MobileNet op that cannot use the MXU (no
contraction dimension: it is C independent k x k stencils), so it runs on
the VPU and is HBM-bandwidth-bound. The XLA lowering materializes the conv
output, then the BatchNorm affine, then the activation, then the AtomNAS
mask — up to four HBM round trips over the widest tensors in the network.
``fused_depthwise_inference`` does all of it in one VMEM residency:

    y = act((dw_conv(x, w)) * scale + shift) * mask

with the BN folded into per-channel scale/shift (eval semantics — training
BN needs batch stats of the conv output, which requires a second pass; the
train path keeps the XLA lowering, which the compiler already fuses well).

A ``jax.custom_vjp`` wrapper makes the fused forward safe to drop into
differentiated code: the backward pass recomputes with the reference XLA
ops. Everything is validated against the ``ops.layers`` reference in Pallas
interpret mode (tests/test_pallas.py) and compiles + runs on real TPU
(scripts/bench_pallas.py).

Status: NOT WIRED INTO THE MODEL — measured and rejected (VERDICT r1 #4
resolved "remove"). On a real v5e (round 2, 2026-07-29), after fixing three
compile-blocking issues the interpreter can't see (scoped-VMEM stack OOM
from whole-image tap unrolls; >2D gathers from strided slices; a Mosaic
crash on rank-5 blocked operands), the honest dependency-chained A/B showed
the fused MBV3-L eval step at 307 ms/step vs 31 ms/step for the plain XLA
lowering at batch 1024 — the kernel LOSES ~10x end-to-end. Root causes:
per-(image, channel-block) grid steps do microseconds of VPU work against
fixed Mosaic dispatch overhead, narrow early blocks (c=16..72) waste up to
8x of every lane-padded VMEM transfer, and the stride-2 phase split costs an
extra HBM round trip that XLA's native conv does not pay. SURVEY.md §2's
rule was "Pallas kernel only if profiling shows a gap" — profiling showed
the opposite, so the model path keeps the XLA lowering (ops/blocks.py) and
this module stays as the measured negative result + harness for future
chips. PROFILE.md records the numbers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .activations import get_activation


def _dw_kernel(*refs, k: int, stride: int, act: str, out_h: int, out_w: int, row_block: int):
    """One (image, channel-block) per grid step, computed in row slabs.

    Three real-hardware constraints shape this kernel (all invisible to the
    interpret-mode tests; all hit on a real v5e):

    - Mosaic stack-allocates every live unrolled temporary, and at 112x112
      spatial with the channel axis lane-padded to 128 a whole-image tap
      unroll needs ~32 MB of scoped VMEM (>16 MB limit). So accumulation
      happens per ``row_block`` output rows: slab temporaries are
      (row_block, out_w, C-block) regardless of image size.
    - Strided (stride>1) vector slices lower to an unsupported >2D gather.
      So the caller phase-splits the padded input into stride^2 planes and
      every tap read here is a *contiguous* slice: output row r needs input
      row r*s + i, which lives in plane i%s at row r + i//s (and likewise
      for columns).
    - A rank-5 blocked operand (phases stacked on one axis) crashes the
      Mosaic compiler outright, so the phase planes arrive as stride^2
      separate rank-4 refs instead.
    """
    s = stride
    x_refs, (w_ref, scale_ref, shift_ref, mask_ref, o_ref) = refs[: s * s], refs[s * s :]
    for r0 in range(0, out_h, row_block):
        rows = min(row_block, out_h - r0)
        acc = None
        for i in range(k):
            for j in range(k):
                ph = (i % s) * s + (j % s)
                sl = x_refs[ph][0, r0 + i // s : r0 + i // s + rows, j // s : j // s + out_w, :]
                term = sl * w_ref[i, j, :]
                acc = term if acc is None else acc + term
        y = acc * scale_ref[0, :] + shift_ref[0, :]
        y = get_activation(act)(y)
        o_ref[0, r0 : r0 + rows, :, :] = (y * mask_ref[0, :]).astype(o_ref.dtype)


# Channel tile: depthwise is channel-independent, so the channel axis blocks
# freely for ANY stride (no halo logic needed, unlike spatial tiling). 128 =
# one VPU lane register width; it bounds per-step VMEM at the widest blocks
# (112x112 spatial x 128ch f32 in+out ~ 13 MB < ~16 MB VMEM; bf16 half that)
# where the old one-image-per-step layout overflowed at real widths.
_C_BLOCK = 128


@functools.partial(jax.jit, static_argnames=("stride", "act", "interpret"))
def _fused_dw_fwd(x, w, scale, shift, mask, *, stride: int, act: str, interpret: bool = False):
    n, h, wd, c = x.shape
    k = w.shape[0]
    pad = k // 2
    s = stride
    # per-channel operands ride as rank-2 (1, C) f32: rank-1 vectors hit
    # two separate Mosaic/XLA layout walls on real v5e (bf16 rank-1 blocks
    # need 256-multiples; f32[240] gets an XLA T(256) layout Mosaic rejects),
    # while (1, C) blocks tile as (sublane=1, lane=C-block) cleanly
    scale = scale.astype(jnp.float32).reshape(1, c)
    shift = shift.astype(jnp.float32).reshape(1, c)
    mask = mask.astype(jnp.float32).reshape(1, c)
    out_h = (h - 1) // s + 1
    out_w = (wd - 1) // s + 1
    # pad to a multiple of s so the s^2 phase planes all have equal shape
    # (the extra zero rows/cols are beyond every tap's reach)
    eh = (-(h + 2 * pad)) % s
    ew = (-(wd + 2 * pad)) % s
    xp = jnp.pad(x, ((0, 0), (pad, pad + eh), (pad, pad + ew), (0, 0)))
    hs = (h + 2 * pad + eh) // s
    ws = (wd + 2 * pad + ew) // s
    # XLA-side phase split: strided slicing is free here but lowers to an
    # unsupported gather inside the kernel (see _dw_kernel docstring); s=1
    # is the identity (one plane, no data movement beyond the pad)
    phases = [xp[:, p::s, q::s, :] for p in range(s) for q in range(s)]

    cb = min(c, _C_BLOCK)
    # slab height: keep each unrolled temporary (row_block x out_w x cb,
    # lanes padded to 128) around ~0.5 MB so ~6 live temps stay well inside
    # the ~16 MB scoped-VMEM stack budget at every spatial size
    row_block = min(out_h, max(8, 2048 // max(out_w, 1)))
    kernel = functools.partial(
        _dw_kernel, k=k, stride=s, act=act, out_h=out_h, out_w=out_w, row_block=row_block
    )
    return pl.pallas_call(
        kernel,
        grid=(n, pl.cdiv(c, cb)),
        in_specs=[pl.BlockSpec((1, hs, ws, cb), lambda i, j: (i, 0, 0, j))] * (s * s)
        + [
            pl.BlockSpec((k, k, cb), lambda i, j: (0, 0, j)),
            pl.BlockSpec((1, cb), lambda i, j: (0, j)),
            pl.BlockSpec((1, cb), lambda i, j: (0, j)),
            pl.BlockSpec((1, cb), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, out_h, out_w, cb), lambda i, j: (i, 0, 0, j)),
        out_shape=jax.ShapeDtypeStruct((n, out_h, out_w, c), x.dtype),
        interpret=interpret,
    )(*phases, w, scale, shift, mask)


def _reference_fwd(x, w, scale, shift, mask, *, stride: int, act: str):
    """The XLA lowering the kernel replaces (also the VJP recompute path)."""
    from jax import lax

    k = w.shape[0]
    pad = k // 2
    c = x.shape[-1]
    y = lax.conv_general_dilated(
        x.astype(jnp.float32),
        w[:, :, None, :].astype(jnp.float32),  # (k,k,1,C) HWIO depthwise
        window_strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )
    y = y * scale + shift
    y = get_activation(act)(y)
    return (y * mask).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def fused_depthwise_inference(x, w, scale, shift, mask, stride: int = 1, act: str = "relu6", interpret: bool = False):
    """Fused dw-conv + folded-BN + activation + mask.

    Args:
      x: (N,H,W,C); w: (k,k,C) depthwise taps; scale/shift: (C,) folded BN
      (scale = gamma*rsqrt(var+eps), shift = beta - mean*scale);
      mask: (C,) AtomNAS atom mask (ones when unused).
      interpret: run the Pallas interpreter (CPU testing).
    """
    return _fused_dw_fwd(x, w, scale, shift, mask, stride=stride, act=act, interpret=interpret)


def _vjp_fwd(x, w, scale, shift, mask, stride, act, interpret):
    y = _fused_dw_fwd(x, w, scale, shift, mask, stride=stride, act=act, interpret=interpret)
    return y, (x, w, scale, shift, mask)


def _vjp_bwd(stride, act, interpret, res, g):
    x, w, scale, shift, mask = res
    # correctness-first backward: differentiate the reference lowering
    _, vjp = jax.vjp(lambda *a: _reference_fwd(*a, stride=stride, act=act), x, w, scale, shift, mask)
    return vjp(g)


fused_depthwise_inference.defvjp(_vjp_fwd, _vjp_bwd)


def fold_bn(gamma, beta, mean, var, eps: float = 1e-5):
    """BN eval affine folded to (scale, shift) for the fused kernel."""
    from .layers import bn_scale_shift

    return bn_scale_shift(gamma, beta, mean, var, eps)
