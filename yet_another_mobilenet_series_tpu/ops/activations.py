"""Activation zoo (reference: mobilenet_base.get_active_fn, SURVEY.md §2 #3).

All piecewise-linear forms are written exactly as the MobileNetV3 paper
defines them (h-swish = x*relu6(x+3)/6) so top-1 parity is not lost to
activation drift (SURVEY.md §7 hard part 2). XLA fuses these into the
surrounding conv epilogues; no Pallas needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def relu(x):
    return jnp.maximum(x, 0)


def relu6(x):
    return jnp.clip(x, 0, 6)


def hsigmoid(x):
    return relu6(x + 3.0) * (1.0 / 6.0)


def hswish(x):
    return x * relu6(x + 3.0) * (1.0 / 6.0)


def sigmoid(x):
    # jax.nn.sigmoid: numerically stable VJP (a hand-rolled 1/(1+exp(-x))
    # yields NaN gradients once exp(-x) overflows at x < -88 in f32).
    return jax.nn.sigmoid(x)


def swish(x):
    # a.k.a. SiLU; used by the AtomNAS "+" variants (SURVEY.md §6)
    return x * jax.nn.sigmoid(x)


def identity(x):
    return x


_ACTIVATIONS = {
    "relu": relu,
    "relu6": relu6,
    "hswish": hswish,
    "h_swish": hswish,
    "hsigmoid": hsigmoid,
    "h_sigmoid": hsigmoid,
    "swish": swish,
    "silu": swish,
    "sigmoid": sigmoid,
    "identity": identity,
    "linear": identity,
}


def get_activation(name: str):
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}") from None
