"""Functional NN core: activations, primitive layers, composite blocks."""

from .activations import get_activation, hsigmoid, hswish, relu, relu6, sigmoid, swish
from .blocks import ConvBNAct, InvertedResidual, SqueezeExcite
from .layers import (
    BatchNorm,
    Conv2D,
    Dense,
    dropout,
    global_avg_pool,
    kaiming_normal_fan_out,
    make_divisible,
)

__all__ = [
    "get_activation", "hswish", "hsigmoid", "relu", "relu6", "sigmoid", "swish",
    "ConvBNAct", "InvertedResidual", "SqueezeExcite",
    "BatchNorm", "Conv2D", "Dense", "dropout", "global_avg_pool",
    "kaiming_normal_fan_out", "make_divisible",
]
