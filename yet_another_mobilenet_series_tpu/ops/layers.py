"""Pure-functional NN primitives: conv / batchnorm / dense / pooling.

Design (SURVEY.md §7 "design stance"): layers are *static specs* — frozen
dataclasses holding only hashable configuration — with ``init(key)`` returning
parameter/state pytrees (plain nested dicts) and ``apply(params, state, x, ...)``
as a pure function. No module objects, no global state; specs are safe to
close over in ``jit``/``shard_map``.

Conventions:
- NHWC activations, HWIO conv kernels (XLA/TPU-native layouts; channels last
  keeps the lane dimension dense on the VPU/MXU).
- Explicit symmetric padding k//2 matches the reference lineage's
  ``torch.nn.Conv2d(padding=k//2)`` (NOT TF 'SAME', which pads asymmetrically
  at stride 2 — a known top-1 parity hazard, SURVEY.md §7 hard part 2).
- Params are float32; matmul/conv compute may run in bfloat16 via
  ``compute_dtype`` while BN statistics stay float32.
- SyncBN: pass ``axis_name`` during training to psum batch moments across the
  data mesh axis — the apex SyncBatchNorm replacement (SURVEY.md §2 #12).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

Array = jax.Array

# the BatchNorm.apply normalize variants (single source of truth — the step
# builders and the A/B bench validate against this same tuple)
BN_MODES = ("exact", "folded", "compute", "fused_vjp", "sdot", "compute_sdot")


# ---------------------------------------------------------------------------
# Initializers (torch-default-compatible: kaiming fan_out for convs, SURVEY.md §7)
# ---------------------------------------------------------------------------


def kaiming_normal_fan_out(key, shape, dtype=jnp.float32):
    """He-normal with fan_out = kh*kw*out_ch (torch's init for conv weights).

    For grouped/depthwise kernels (HWIO with I = in/groups) fan_out is still
    kh*kw*O per torch semantics.
    """
    kh, kw, _, o = shape
    fan_out = kh * kw * o
    std = math.sqrt(2.0 / fan_out)
    return jax.random.normal(key, shape, dtype) * jnp.asarray(std, dtype)


def normal_init(std):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.normal(key, shape, dtype) * jnp.asarray(std, dtype)

    return init


# ---------------------------------------------------------------------------
# Conv2D
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Conv2D:
    """2-D convolution spec. groups=in_channels gives a depthwise conv, which
    XLA lowers via ``feature_group_count`` (the cuDNN-depthwise replacement,
    SURVEY.md §2 native table)."""

    in_channels: int
    out_channels: int
    kernel_size: int = 1
    stride: int = 1
    groups: int = 1
    use_bias: bool = False

    def __post_init__(self):
        if self.in_channels % self.groups or self.out_channels % self.groups:
            raise ValueError(f"channels ({self.in_channels}->{self.out_channels}) not divisible by groups={self.groups}")

    def init(self, key) -> dict:
        k = self.kernel_size
        shape = (k, k, self.in_channels // self.groups, self.out_channels)
        params = {"w": kaiming_normal_fan_out(key, shape)}
        if self.use_bias:
            params["b"] = jnp.zeros((self.out_channels,), jnp.float32)
        return params

    def apply(self, params: dict, x: Array, *, compute_dtype=jnp.float32, as_dot: bool = False) -> Array:
        """as_dot lowers a 1x1 ungrouped conv as an explicit matmul
        (`(N,H,W,Cin) @ (Cin,Cout)`): forward is the same contraction XLA
        canonicalizes 1x1 convs to, but the WEIGHT GRADIENT of a dot is
        guaranteed to lower as another dot (MXU) — the round-2 trace showed
        25.3% of step time in `multiply_add_fusion` weight-grad reductions
        (PROFILE.md), and this removes XLA's freedom to pick that lowering
        for the 1x1s. No-op for k>1 or grouped convs. Param layout is
        unchanged (HWIO, reshaped at apply), so checkpoints are identical."""
        w = params["w"].astype(compute_dtype)
        x = x.astype(compute_dtype)
        if as_dot and self.kernel_size == 1 and self.groups == 1:
            if self.stride > 1:
                # 1x1 stride-s conv == subsample then matmul (pad is 0)
                x = x[:, :: self.stride, :: self.stride, :]
            y = x @ w.reshape(self.in_channels, self.out_channels)
        else:
            pad = self.kernel_size // 2
            y = lax.conv_general_dilated(
                x,
                w,
                window_strides=(self.stride, self.stride),
                padding=((pad, pad), (pad, pad)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=self.groups,
            )
        if self.use_bias:
            y = y + params["b"].astype(compute_dtype)
        # remat landmark: train.remat_policy="save_conv" saves exactly these
        # (the MXU results) and recomputes the cheap BN/act elementwise chain
        # in backward, so normalized activations are never materialized
        # (train/steps.py; identity when no jax.checkpoint wraps the forward)
        return checkpoint_name(y, "conv_out")


# ---------------------------------------------------------------------------
# BatchNorm (with cross-replica sync)
# ---------------------------------------------------------------------------


def _finalize_moments(s1, s2, n_local, axis_name):
    """Shared psum + mean/biased-var tail of both stat paths — one copy, so
    a future change to the clamp or the psum structure cannot drift the
    modes apart below the parity tests' tolerance."""
    n = jnp.asarray(n_local, jnp.float32)
    if axis_name is not None:
        s1 = lax.psum(s1, axis_name)
        s2 = lax.psum(s2, axis_name)
        n = lax.psum(n, axis_name)
    mean = s1 / n
    var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)  # biased
    return mean, var, n


def _bn_moments(x, axis_name):
    """Global (psum'd) f32 moments of x over N,H,W: (mean, var_biased, n).
    f32 accumulators reduce the input dtype directly — bit-equal to casting
    first, with no materialized f32 copy of the activation."""
    n_local = x.shape[0] * x.shape[1] * x.shape[2]
    s1 = jnp.sum(x, axis=(0, 1, 2), dtype=jnp.float32)
    s2 = jnp.sum(jnp.square(x.astype(jnp.float32)), axis=(0, 1, 2))
    return _finalize_moments(s1, s2, n_local, axis_name)


def _bn_moments_dot(x, axis_name):
    """Batch moments computed as MXU contractions instead of VPU reduces —
    the round-4 attack candidate on the trace's 51.8% convert_reduce_fusion
    share (PROFILE.md): s1 = ones·x is a plain dot; s2 = Σ_nhw x² is a
    C-batched self-contraction (batch dim C, contract NHW), whose bf16
    products are EXACT in the f32 accumulator (8-bit mantissas double to 16
    < 24). Forcing dot lowerings also forces the BACKWARD companions of the
    stat reductions onto the MXU (autodiff transposes a dot to dots).
    Within f32 accumulation-order rounding (~1e-7 rel) of _bn_moments —
    NOT bit-identical, hence a separate opt-in mode. The exact-products
    argument above is for bf16 INPUTS; f32 inputs on the MXU would be
    silently truncated to bf16 under default precision (~1e-3 stat error,
    invisible to the CPU parity tests), so f32 requests HIGHEST precision —
    the bf16 training path keeps the fast default."""
    c = x.shape[-1]
    xt = x.reshape(-1, c)
    n_local = xt.shape[0]
    ones = jnp.ones((n_local,), x.dtype)
    prec = lax.Precision.HIGHEST if x.dtype == jnp.float32 else lax.Precision.DEFAULT
    s1 = lax.dot_general(ones, xt, (((0,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32, precision=prec)
    s2 = lax.dot_general(xt, xt, (((0,), (0,)), ((1,), (1,))),
                         preferred_element_type=jnp.float32, precision=prec)
    return _finalize_moments(s1, s2, n_local, axis_name)


def _bn_train_fused(x, gamma, beta, eps, axis_name):
    y, mean, var, _ = _bn_train_fused_fwd_impl(x, gamma, beta, eps, axis_name)
    return y, mean, var


def _bn_train_fused_fwd_impl(x, gamma, beta, eps, axis_name):
    mean, var, n = _bn_moments(x, axis_name)
    inv = lax.rsqrt(var + eps)
    scale = gamma * inv
    bias = beta - mean * scale
    y = (x.astype(jnp.float32) * scale + bias).astype(x.dtype)
    return y, mean, var, (inv, n)


def _bn_train_fused_fwd(x, gamma, beta, eps, axis_name):
    # symbolic_zeros=True (see defvjp below) wraps each differentiable
    # primal in a CustomVJPPrimal carrier: unwrap to the actual arrays
    x, gamma, beta = x.value, gamma.value, beta.value
    y, mean, var, (inv, n) = _bn_train_fused_fwd_impl(x, gamma, beta, eps, axis_name)
    # residuals are the bf16 input + per-channel f32 stats — x_hat and any
    # f32 copy of the activation are recomputed, never stored
    return (y, mean, var), (x, gamma, mean, inv, n)


def _bn_train_fused_bwd(eps, axis_name, res, cts):
    """Closed-form BN backward through the batch statistics:

        dβ = Σ_local dy;  dγ = Σ_local dy·x̂;
        dx = γ·inv · (dy − psum(dβ)/n − x̂·psum(dγ)/n)    with n GLOBAL

    The asymmetry is the per-device gradient contract autodiff of the other
    bn_modes produces under the production shard_maps (parallel/dp.py,
    check_vma=False), pinned by tests/test_ops.py's sharded-contract test:

    - γ/β are REPLICATED params: each device returns its local partial sum
      and the training step's grad pmean (train/steps.py) — or the ZeRO
      psum_scatter — combines them. A psum here would double-count
      (device_count× BN affine grads; caught by review in round 3).
    - x is SHARDED: each shard's cotangent must be complete immediately,
      and x_e affects every device's outputs through the psum'd moments, so
      the correction terms need the GLOBAL sums (the transpose of the
      forward psum).

    The two local reductions fuse into ONE pass over (x, dy); dx is one
    more elementwise pass. Cotangents of the mean/var outputs must be
    symbolically zero: they feed only the running-stat state, which the
    training loss never differentiates (train/steps.py returns new_state as
    aux) — and that assumption is ENFORCED below (ADVICE r3 #1), so a
    future loss term reading the batch stats fails loudly at trace time
    instead of silently training with zero stat-gradients. The var
    zero-clamp in _bn_moments is treated as inactive (it only engages when
    catastrophic cancellation makes var numerically negative)."""
    del eps  # static; backward needs only the saved residuals
    x, gamma, mean, inv, n = res
    dy, dmean_ct, dvar_ct = cts
    zero = jax.custom_derivatives.SymbolicZero
    if not (isinstance(dmean_ct, zero) and isinstance(dvar_ct, zero)):
        raise TypeError(
            "bn_mode='fused_vjp' received non-zero cotangents for the batch "
            "mean/var outputs; its closed-form backward discards them by "
            "contract. A loss term differentiating the batch statistics "
            "(e.g. a stat regularizer) must use an autodiff bn_mode "
            "('exact'/'folded') or extend _bn_train_fused_bwd."
        )
    if isinstance(dy, zero):
        # nothing differentiates y either: all three gradients vanish
        return jnp.zeros_like(x), jnp.zeros_like(gamma), jnp.zeros_like(gamma)
    dyf = dy.astype(jnp.float32)
    x_hat = (x.astype(jnp.float32) - mean) * inv
    dbeta = jnp.sum(dyf, axis=(0, 1, 2))
    dgamma = jnp.sum(dyf * x_hat, axis=(0, 1, 2))
    s1, s2 = dbeta, dgamma
    if axis_name is not None:
        s1 = lax.psum(s1, axis_name)
        s2 = lax.psum(s2, axis_name)
    dx = (gamma * inv) * (dyf - s1 / n - x_hat * (s2 / n))
    return dx.astype(x.dtype), dgamma, dbeta


_bn_train_fused = jax.custom_vjp(_bn_train_fused, nondiff_argnums=(3, 4))
# symbolic_zeros=True so the backward can DETECT (and reject) a real
# cotangent on the mean/var outputs rather than silently dropping it
_bn_train_fused.defvjp(_bn_train_fused_fwd, _bn_train_fused_bwd, symbolic_zeros=True)


@dataclass(frozen=True)
class BatchNorm:
    """BatchNorm over N,H,W with torch semantics:

    - normalization uses biased batch variance,
    - running stats update ``running = (1-m)*running + m*batch`` with
      momentum m (torch default 0.1) and *unbiased* batch variance,
    - when ``axis_name`` is given in training, batch moments are allreduced
      with ``lax.psum`` so statistics are exact global mean/var across
      replicas — matching apex SyncBatchNorm's two-pass moments
      (SURVEY.md §7 hard part 3).

    The scale vector ``gamma`` is the AtomNAS prune handle (SURVEY.md §3.2).
    """

    num_features: int
    momentum: float = 0.1
    eps: float = 1e-5

    def init(self, key=None) -> tuple[dict, dict]:
        params = {
            "gamma": jnp.ones((self.num_features,), jnp.float32),
            "beta": jnp.zeros((self.num_features,), jnp.float32),
        }
        state = {
            "mean": jnp.zeros((self.num_features,), jnp.float32),
            "var": jnp.ones((self.num_features,), jnp.float32),
        }
        return params, state

    def apply(
        self,
        params: dict,
        state: dict,
        x: Array,
        *,
        train: bool,
        axis_name: str | None = None,
        mode: str = "exact",
    ) -> tuple[Array, dict]:
        """mode selects the NORMALIZE expression only — batch statistics are
        bit-identical f32 accumulations in every mode (reducing the input
        dtype with an f32 accumulator equals casting first, element-for-
        element, and never materializes an f32 copy of the activation):

        - "exact"  — (f32(x) - mean) * (gamma*rsqrt(var+eps)) + beta. The
          round-2 TPU trace shows this step's 51.8% convert_reduce_fusion
          cost concentrated around BN (PROFILE.md "Where the time goes");
          the f32-upcast expression shared between the stat-reduce and the
          normalize is the suspected extra-HBM-traffic source.
        - "folded" — per-channel scale = gamma*rsqrt(var+eps) and
          bias = beta - mean*scale are precomputed (f32, C-sized, cheap);
          the tensor-wide work is a single FMA x*scale+bias with the f32
          convert inline in its own fusion. Differs from "exact" only by
          f32 rounding of the re-association (~1e-7 relative) — invisible
          under a bf16 output cast.
        - "compute" — like "folded" but scale/bias are cast to x.dtype and
          the FMA runs entirely in the compute dtype (bf16): halves the
          elementwise VPU width and drops both converts. Costs ~2-3 ulps of
          bf16 precision on y; opt-in for perf A/B.
        - "fused_vjp" — the "folded" forward under a custom VJP whose
          backward is the closed-form BN gradient: residuals are pinned to
          the bf16 input + per-channel f32 stats (x̂ and f32 activation
          copies are recomputed, never stored), and the dγ/dβ reductions
          fuse into one pass over (x, dy). Values equal "folded" exactly;
          gradients equal autodiff within reduction-order rounding.
        - "sdot" — the "folded" normalize, but batch statistics computed as
          MXU dots (_bn_moments_dot): the one family whose statistics are
          not bit-identical to the others (f32 accumulation order on the
          MXU; ~1e-7 rel). Opt-in for the hardware A/B against the VPU
          stat-reduce share of the trace.
        - "compute_sdot" — the "compute" (bf16 FMA) normalize over the
          MXU-dot statistics: the composite of the two independent levers,
          so the A/B can measure their combination directly instead of
          inferring additivity.
        """
        if mode not in BN_MODES:
            raise ValueError(f"unknown bn mode {mode!r}")
        out_dtype = x.dtype

        def running(mean, var, n):
            m = self.momentum
            unbiased = var * (n / jnp.maximum(n - 1.0, 1.0))
            return {
                "mean": (1.0 - m) * state["mean"] + m * mean,
                "var": (1.0 - m) * state["var"] + m * unbiased,
            }

        if train and mode == "fused_vjp":
            y, mean, var = _bn_train_fused(x, params["gamma"], params["beta"], self.eps, axis_name)
            # lax.psum of the literal 1 is constant-folded to the axis size
            n = jnp.asarray(x.shape[0] * x.shape[1] * x.shape[2], jnp.float32)
            if axis_name is not None:
                n = n * lax.psum(1, axis_name)
            return y, running(mean, var, n)
        if train:
            moments = _bn_moments_dot if mode in ("sdot", "compute_sdot") else _bn_moments
            mean, var, n = moments(x, axis_name)
            new_state = running(mean, var, n)
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        scale = lax.rsqrt(var + self.eps) * params["gamma"]
        if mode == "exact":
            y = (x.astype(jnp.float32) - mean) * scale + params["beta"]
        elif mode in ("compute", "compute_sdot"):
            bias = params["beta"] - mean * scale
            y = x * scale.astype(out_dtype) + bias.astype(out_dtype)
        else:  # "folded"/"sdot", and eval-mode "fused_vjp" (same expression)
            bias = params["beta"] - mean * scale
            y = x.astype(jnp.float32) * scale + bias
        return y.astype(out_dtype), new_state


# ---------------------------------------------------------------------------
# Dense
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Dense:
    in_features: int
    out_features: int
    use_bias: bool = True
    init_std: float = 0.01  # reference lineage: classifier ~ N(0, 0.01)

    def init(self, key) -> dict:
        w = normal_init(self.init_std)(key, (self.in_features, self.out_features))
        params = {"w": w}
        if self.use_bias:
            params["b"] = jnp.zeros((self.out_features,), jnp.float32)
        return params

    def apply(self, params: dict, x: Array, *, compute_dtype=jnp.float32) -> Array:
        y = x.astype(compute_dtype) @ params["w"].astype(compute_dtype)
        if self.use_bias:
            y = y + params["b"].astype(compute_dtype)
        return y


# ---------------------------------------------------------------------------
# Stateless helpers
# ---------------------------------------------------------------------------


def bn_scale_shift(gamma, beta, mean, var, eps: float = 1e-5):
    """Eval-mode BN collapsed to a per-channel affine: scale = gamma *
    rsqrt(var + eps), shift = beta - mean * scale — the single source of the
    fold used by the Pallas eval kernel (ops/pallas_kernels.fold_bn) and the
    serving weight transform (serve/export.py), so the two can never drift."""
    scale = gamma * lax.rsqrt(var + eps)
    return scale, beta - mean * scale


def global_avg_pool(x: Array, keepdims: bool = False) -> Array:
    """Mean over H,W. Computed in float32 (bf16 accumulation over 49+ terms
    loses precision that measurably hurts SE gates and the head)."""
    return jnp.mean(x.astype(jnp.float32), axis=(1, 2), keepdims=keepdims).astype(x.dtype)


def dropout(rng, x: Array, rate: float, train: bool) -> Array:
    if not train or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def make_divisible(v: float, divisor: int = 8, min_value: int | None = None) -> int:
    """Channel rounding used throughout the MobileNet family (reference:
    mobilenet_base.make_divisible). Never rounds down by more than 10%."""
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v
