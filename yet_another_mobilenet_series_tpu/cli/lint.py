"""yamt-lint entry point — ``python -m yet_another_mobilenet_series_tpu.cli.lint
[paths...]``, sibling of cli.train/cli.profile.

Thin wrapper: the implementation lives in analysis/cli.py (also reachable as
``python -m yet_another_mobilenet_series_tpu.analysis``). Rules and the
suppression syntax are documented in docs/LINT.md.
"""

from __future__ import annotations

import sys

from ..analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
