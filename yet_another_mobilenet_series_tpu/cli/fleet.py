"""Fleet entry point — ``python -m yet_another_mobilenet_series_tpu.cli.fleet
app:<yaml> serve.bundle=<dir> [key=value ...]``.

Spawns and supervises N ``cli/serve.py --listen`` replica subprocesses on
ephemeral ports and puts the fleet router (serve/router.py) in front of them
as an ordinary frontend — same endpoints, same typed statuses, same
``X-Request-Id`` threading — so to a client the fleet IS one replica, just
one that survives the death of any of its processes. The supervisor process
itself never imports jax: replicas own the device; the parent owns policy.

What runs here:

- **spawn**: each replica is ``cli/serve.py`` with the SAME config plus per
  -slot overrides (``serve.listen.port=0``, ``serve.listen.replica_id=r<i>``,
  its own ``train.log_dir``). The bound port is read from the replica's
  atomically-renamed ``listen_addr.json`` (a poll never sees partial JSON)
  and cross-checked against the child pid, bounded by
  ``serve.fleet.spawn_timeout_s``.
- **supervision**: a guarded thread restarts any replica that exits while
  wanted (``fleet.restarts``), with per-slot exponential backoff
  (``restart_backoff_ms`` doubling to ``restart_backoff_max_s``) so a
  crash-looping artifact cannot spin the host. Every membership change is
  pushed to the router (``on_change`` -> ``Router.set_backends``).
- **scaling**: :meth:`FleetSupervisor.scale_to` adds replicas (new slots)
  or drains the newest ones — the autoscaler's one dependency.
- **rolling restart** (SIGHUP): replicas drain and respawn ONE AT A TIME,
  each waiting for its successor to bind before the next drain starts, so
  capacity never drops by more than one replica.
- **replica chaos** (``serve.fleet.chaos``): a seeded schedule of kill -9
  against random live replicas mid-load (``fleet.chaos_kills``) — the
  process-granular twin of serve/faults.py, exercising restart-on-exit,
  router ejection/readmission, and transport-retry for real.

SIGTERM/SIGINT: stop accepting at the router, then drain every replica
sequentially (each bounded by its own SIGTERM drain), then exit 0.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

from ..config import Config, parse_cli
from ..obs import device as obs_device
from ..obs import registry as obs_registry
from ..obs import trace as obs_trace
from ..obs.fleet import FleetFederation, FlightRecorder
from ..obs.watchdog import StallWatchdog
from ..serve.autoscale import Autoscaler
from ..serve.brownout import BrownoutController
from ..serve.frontend import Frontend, write_listen_addr
from ..serve.hedge import ROUTER_LATENCY, Hedger
from ..serve.netchaos import NetChaosTier
from ..serve.router import Router
from ..serve.signals import SignalReader, SLOTracker
from ..utils.logging import Logger, emit

# repo root (the package's parent): child interpreters must resolve the
# package no matter where the operator launched the supervisor from
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class FleetSpawnError(RuntimeError):
    """A replica failed to come up (died early or never published its
    listen_addr.json inside spawn_timeout_s)."""


# Why not PR_SET_PDEATHSIG: the kernel delivers it when the forking THREAD
# exits, not the process — the supervisor spawns from short-lived threads,
# so pdeathsig SIGTERMed freshly-bound replicas the moment their spawn
# thread finished (measured). The orphan guard lives on the REPLICA side
# instead: cli/serve.py polls getppid() against this env var and
# self-drains when its supervisor process is gone (kill -9 included), so a
# dead supervisor can never leak replicas — the process-level YAMT015
# hazard, closed portably.
ORPHAN_ENV = "YAMT_FLEET_PARENT"


class ReplicaHandle:
    """One replica subprocess: spawn, readiness, drain, kill."""

    def __init__(self, slot: int, argv: list[str], log_dir: str, *,
                 spawn_timeout_s: float = 120.0, env: dict | None = None):
        self.slot = slot
        self.argv = argv
        self.log_dir = log_dir
        self.spawn_timeout_s = spawn_timeout_s
        self._env = env
        self._proc: subprocess.Popen | None = None
        self._log_file = None
        self.addr: dict | None = None

    @property
    def pid(self) -> int | None:
        return self._proc.pid if self._proc is not None else None

    @property
    def returncode(self) -> int | None:
        return self._proc.returncode if self._proc is not None else None

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def spawn(self) -> "ReplicaHandle":
        """Launch the replica and block until it publishes its bound address
        (atomic listen_addr.json) or the spawn budget runs out — in which
        case the half-started child is killed, never leaked."""
        os.makedirs(self.log_dir, exist_ok=True)
        addr_path = os.path.join(self.log_dir, "listen_addr.json")
        if os.path.exists(addr_path):
            os.remove(addr_path)  # a stale address from a previous incarnation
        env = dict(os.environ if self._env is None else self._env)
        env["PYTHONPATH"] = _PKG_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        # the replica self-drains if THIS process disappears (see ORPHAN_ENV)
        env[ORPHAN_ENV] = str(os.getpid())
        self._log_file = open(os.path.join(self.log_dir, "replica.log"), "ab")
        self._proc = subprocess.Popen(
            self.argv, stdout=self._log_file, stderr=subprocess.STDOUT, env=env
        )
        try:
            self.addr = self._wait_ready(addr_path)
        except Exception:
            # the exception edge must not leak a half-started child: bounded
            # terminate -> kill, then re-raise the spawn failure
            self.kill(sig=signal.SIGKILL)
            raise
        return self

    def _wait_ready(self, addr_path: str) -> dict:
        deadline = time.monotonic() + self.spawn_timeout_s
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                raise FleetSpawnError(
                    f"replica {self.slot} exited rc={self._proc.returncode} before binding "
                    f"(see {self.log_dir}/replica.log)"
                )
            if os.path.exists(addr_path):
                with open(addr_path) as f:
                    addr = json.load(f)  # whole JSON by the rename contract
                if addr.get("pid") == self._proc.pid:
                    return addr
            time.sleep(0.1)
        raise FleetSpawnError(
            f"replica {self.slot} never published {addr_path} within {self.spawn_timeout_s:.0f}s"
        )

    def drain(self, timeout_s: float = 30.0) -> bool:
        """SIGTERM -> bounded wait (the replica's own drain path runs);
        escalate to SIGKILL if the budget runs out. True = clean exit."""
        if self._proc is None:
            return True
        clean = True
        try:
            if self._proc.poll() is None:
                self._proc.send_signal(signal.SIGTERM)
            try:
                self._proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                clean = False
                self._proc.kill()
                self._proc.wait(timeout=10.0)
        except ProcessLookupError:
            pass  # already reaped
        self._close_log()
        return clean

    def send_signal(self, sig: int) -> bool:
        """Deliver ``sig`` WITHOUT waiting (the chaos hook: a kill -9 must
        not politely reap before the supervisor notices the death)."""
        if self._proc is None or self._proc.poll() is not None:
            return False
        try:
            self._proc.send_signal(sig)
        except ProcessLookupError:
            return False
        return True

    def kill(self, sig: int = signal.SIGKILL) -> None:
        """Immediate (chaos / cleanup) kill with a bounded reap."""
        if self._proc is None:
            return
        try:
            if self._proc.poll() is None:
                self._proc.send_signal(sig)
            self._proc.wait(timeout=10.0)
        except (ProcessLookupError, subprocess.TimeoutExpired):
            pass
        self._close_log()

    def _close_log(self) -> None:
        if self._log_file is not None:
            self._log_file.close()
            self._log_file = None


class _Slot:
    """Supervisor bookkeeping for one replica position."""

    __slots__ = ("idx", "handle", "wanted", "busy", "generation",
                 "consecutive_crashes", "next_restart_t", "last_spawn_t")

    def __init__(self, idx: int):
        self.idx = idx
        self.handle: ReplicaHandle | None = None
        self.wanted = True
        self.busy = False  # a spawn/drain is in flight for this slot
        self.generation = 0
        self.consecutive_crashes = 0
        self.next_restart_t = 0.0
        self.last_spawn_t = 0.0


class FleetSupervisor:
    """Spawns, restarts, scales, and drains the replica set."""

    # a replica that survived this long resets its crash-backoff ladder
    CRASH_RESET_S = 30.0

    def __init__(
        self,
        *,
        replica_argv: list[str],
        log_dir: str,
        replicas: int = 2,
        restart_backoff_ms: float = 200.0,
        restart_backoff_max_s: float = 5.0,
        spawn_timeout_s: float = 120.0,
        drain_timeout_s: float = 30.0,
        supervise_poll_s: float = 0.2,
        per_slot_argv: dict[int, list[str]] | None = None,
        on_change=None,
        spawn_fn=None,
        logger=None,
    ):
        self._replica_argv = list(replica_argv)
        self._log_dir = log_dir
        self._n_initial = max(1, int(replicas))
        self._backoff_s = restart_backoff_ms / 1e3
        self._backoff_max_s = restart_backoff_max_s
        self._spawn_timeout_s = spawn_timeout_s
        self._drain_timeout_s = drain_timeout_s
        self._supervise_poll_s = supervise_poll_s
        self._per_slot_argv = dict(per_slot_argv or {})
        self._on_change = on_change  # e.g. Router.set_backends (addresses list)
        self._spawn_fn = spawn_fn or self._spawn_real
        self._log = logger
        self._lock = threading.Lock()
        self._slots: dict[int, _Slot] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._reg = obs_registry.get_registry()

    # -- spawning ------------------------------------------------------------

    def _spawn_real(self, slot: int) -> ReplicaHandle:
        argv = [
            sys.executable, "-m", "yet_another_mobilenet_series_tpu.cli.serve",
            *self._replica_argv,
            "serve.listen.enable=true",
            "serve.listen.port=0",
            f"serve.listen.replica_id=r{slot}",
            f"train.log_dir={os.path.join(self._log_dir, f'r{slot}')}",
            *self._per_slot_argv.get(slot, []),
        ]
        return ReplicaHandle(
            slot, argv, os.path.join(self._log_dir, f"r{slot}"),
            spawn_timeout_s=self._spawn_timeout_s,
        ).spawn()

    def _emit(self, msg: str) -> None:
        if self._log is not None:
            self._log.log(msg)
        else:
            emit(msg)

    def _spawn_slot(self, slot: _Slot) -> bool:
        slot.last_spawn_t = time.monotonic()
        try:
            handle = self._spawn_fn(slot.idx)
        except Exception as e:  # noqa: BLE001 — a failed spawn backs off, not crashes
            self._reg.counter("fleet.spawn_failures").inc()
            self._emit(f"[fleet] spawn r{slot.idx} failed: {type(e).__name__}: {e}")
            return False
        with self._lock:
            slot.handle = handle
            slot.generation += 1
        self._reg.counter("fleet.spawns").inc()
        self._emit(f"[fleet] replica r{slot.idx} up: pid={handle.pid} "
                   f"addr={handle.addr['host']}:{handle.addr['port']}")
        return True

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        if self._thread is not None:
            raise RuntimeError("fleet already started")
        with self._lock:
            for i in range(self._n_initial):
                self._slots[i] = _Slot(i)
        # parallel first spawn: N children import/compile concurrently
        threads = [
            threading.Thread(target=self._first_spawn_guarded, args=(s,), daemon=True,
                             name=f"fleet-spawn-r{s.idx}")
            for s in self._slots.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if not self.addresses():
            self.stop()
            raise FleetSpawnError("no replica came up; fleet cannot start")
        self._notify()
        self._stop.clear()
        self._thread = threading.Thread(target=self._supervise, name="fleet-supervise", daemon=True)
        self._thread.start()
        return self

    def _first_spawn_guarded(self, slot: _Slot) -> None:
        try:  # YAMT011: a dead spawn thread would silently halve the fleet
            self._spawn_slot(slot)
        except Exception as e:  # noqa: BLE001 — contain; start() checks coverage
            self._reg.counter("serve.thread_crashes").inc()
            self._emit(f"[fleet] spawn thread r{slot.idx} crashed: {type(e).__name__}: {e}")

    def stop(self) -> None:
        """Stop supervising, then drain every replica sequentially (each
        bounded); the fleet exits with no child left behind."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        with self._lock:
            slots = list(self._slots.values())
            for s in slots:
                s.wanted = False
        for s in slots:
            if s.handle is not None:
                s.handle.drain(self._drain_timeout_s)
        self._notify()

    # -- supervision (restart-on-exit with backoff) --------------------------

    def _supervise(self) -> None:
        try:  # YAMT011: the supervisor dying silently orphans the fleet
            while not self._stop.wait(self._supervise_poll_s):
                self._supervise_once()
        except Exception as e:  # noqa: BLE001 — contain, count, report
            self._reg.counter("serve.thread_crashes").inc()
            self._emit(f"[fleet] supervise thread crashed: {type(e).__name__}: {e}")

    def _supervise_once(self) -> None:
        now = time.monotonic()
        with self._lock:
            slots = [s for s in self._slots.values() if s.wanted and not s.busy]
        changed = False
        for s in slots:
            if s.handle is not None and s.handle.alive():
                if s.consecutive_crashes and now - s.last_spawn_t > self.CRASH_RESET_S:
                    s.consecutive_crashes = 0  # survived: the loop is over
                continue
            if s.handle is not None:
                # died while wanted: schedule the restart with backoff
                rc = s.handle.returncode
                s.handle._close_log()
                s.handle = None
                changed = True
                backoff = min(self._backoff_s * (2 ** s.consecutive_crashes), self._backoff_max_s)
                s.consecutive_crashes += 1
                s.next_restart_t = now + backoff
                self._emit(f"[fleet] replica r{s.idx} exited rc={rc}; "
                           f"restart in {backoff * 1e3:.0f}ms")
            if s.handle is None and now >= s.next_restart_t:
                self._reg.counter("fleet.restarts").inc()
                if self._spawn_slot(s):
                    changed = True
                else:
                    backoff = min(self._backoff_s * (2 ** s.consecutive_crashes),
                                  self._backoff_max_s)
                    s.consecutive_crashes += 1
                    s.next_restart_t = time.monotonic() + backoff
        if changed:
            self._notify()

    def _notify(self) -> None:
        self._reg.gauge("fleet.replicas").set(self.n_replicas)
        if self._on_change is not None:
            try:
                self._on_change(self.addresses())
            except Exception as e:  # noqa: BLE001 — a router hiccup must not kill supervision
                self._emit(f"[fleet] membership notify failed: {type(e).__name__}: {e}")

    # -- introspection -------------------------------------------------------

    @property
    def n_replicas(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots.values() if s.wanted)

    def addresses(self) -> list[tuple[str, int]]:
        with self._lock:
            return [
                (s.handle.addr["host"], s.handle.addr["port"])
                for s in self._slots.values()
                if s.wanted and s.handle is not None and s.handle.addr is not None
            ]

    def replicas(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "slot": s.idx,
                    "wanted": s.wanted,
                    "alive": s.handle.alive() if s.handle else False,
                    "pid": s.handle.pid if s.handle else None,
                    "addr": s.handle.addr if s.handle else None,
                    "generation": s.generation,
                    "consecutive_crashes": s.consecutive_crashes,
                }
                for s in self._slots.values()
            ]

    # -- scaling / rolling restart / chaos -----------------------------------

    def scale_to(self, n: int) -> int:
        """Grow or shrink to ``n`` replicas (blocking: spawns wait for bind,
        drains wait for exit). Shrink drains the NEWEST slots first. Returns
        the achieved count."""
        n = max(1, int(n))
        with self._lock:
            wanted = sorted(s.idx for s in self._slots.values() if s.wanted)
            grow = n - len(wanted)
            new_slots: list[_Slot] = []
            victims: list[_Slot] = []
            if grow > 0:
                next_idx = (max(self._slots) + 1) if self._slots else 0
                for i in range(grow):
                    s = _Slot(next_idx + i)
                    s.busy = True
                    self._slots[s.idx] = s
                    new_slots.append(s)
            elif grow < 0:
                for idx in wanted[grow:]:
                    s = self._slots[idx]
                    s.wanted = False
                    s.busy = True
                    victims.append(s)
        for s in new_slots:
            self._spawn_slot(s)
            with self._lock:
                s.busy = False
        for s in victims:
            if s.handle is not None:
                s.handle.drain(self._drain_timeout_s)
            with self._lock:
                s.handle = None
                del self._slots[s.idx]
        if new_slots or victims:
            self._notify()
        return self.n_replicas

    def rolling_restart(self) -> int:
        """Drain + respawn every replica ONE AT A TIME (capacity never drops
        by more than one). Returns the number restarted."""
        with self._lock:
            order = sorted(s.idx for s in self._slots.values() if s.wanted)
        restarted = 0
        for idx in order:
            with self._lock:
                s = self._slots.get(idx)
                if s is None or not s.wanted or s.busy:
                    continue
                s.busy = True
            try:
                if s.handle is not None:
                    s.handle.drain(self._drain_timeout_s)
                    s.handle = None
                    self._notify()  # the router must stop routing here NOW
                if self._spawn_slot(s):
                    restarted += 1
                    s.consecutive_crashes = 0
            finally:
                with self._lock:
                    s.busy = False
            self._notify()
        self._reg.counter("fleet.rolling_restarts").inc()
        return restarted

    def kill_replica(self, slot: int | None = None, *, sig: int = signal.SIGKILL,
                     rng: random.Random | None = None) -> int | None:
        """Chaos: kill one live replica (seeded-random when ``slot`` is
        None). The supervise loop restarts it; the router ejects it the
        moment a poll or dispatch hits the dead socket."""
        with self._lock:
            live = [s for s in self._slots.values()
                    if s.wanted and s.handle is not None and s.handle.alive()]
            if not live:
                return None
            target = (
                next((s for s in live if s.idx == slot), None) if slot is not None
                else (rng or random).choice(live)
            )
            if target is None:
                return None
            handle = target.handle
        self._reg.counter("fleet.chaos_kills").inc()
        self._emit(f"[fleet] CHAOS: sending signal {sig} to replica r{target.idx} "
                   f"(pid {handle.pid})")
        if not handle.send_signal(sig):
            return None
        return target.idx

    def pick_live_slot(self, rng: random.Random | None = None) -> int | None:
        """One seeded-random live slot index (the degrade chaos victim)."""
        with self._lock:
            live = [s for s in self._slots.values()
                    if s.wanted and s.handle is not None and s.handle.alive()]
        return (rng or random).choice(live).idx if live else None

    def signal_replica(self, slot: int, sig: int) -> bool:
        """Deliver ``sig`` to one slot's live replica with NO lifecycle
        bookkeeping — the degrade-chaos pulse path (SIGSTOP/SIGCONT leave
        the process alive; the supervisor must not treat it as an exit)."""
        with self._lock:
            s = self._slots.get(slot)
            handle = s.handle if s is not None and s.wanted else None
        if handle is None:
            return False
        return handle.send_signal(sig)


class FleetChaos:
    """Seeded chaos schedule against the live fleet (serve.fleet.chaos).

    Three modes:

    - ``kill`` — the PR-12 crash drill: SIGKILL/SIGTERM a seeded live
      replica after ``kill_after_s`` (repeating every ``kill_period_s``);
      exercises restart-on-exit, crash ejection, transport retry.
    - ``degrade`` — the GRAY-failure drill: the seeded victim is pulsed
      SIGSTOP for ``degrade_stop_ms`` out of every ``degrade_period_ms``
      over ``degrade_duration_s``, then released with a final SIGCONT. The
      process never exits — sockets stay open, /healthz still answers
      between pulses — it just gets SLOW (a GC pause / noisy-neighbor
      stand-in), which only the router's latency-based soft ejection can
      act on. Counted ``fleet.chaos_degrades``; pulses are bounded and the
      stop path always delivers the releasing SIGCONT so a cancelled drill
      cannot leave a replica frozen.
    - ``partition`` — the NETWORK drill (PR 15): the seeded victim's
      netchaos proxy (serve/netchaos.py, requires the
      ``serve.fleet.netchaos`` tier) is switched to the configured fault
      shape — blackhole, reset, half-open, response loss — for
      ``degrade_duration_s``, then healed. The replica process never
      notices; only the LINK misbehaves, which is exactly the failure the
      connect/read timeout split and lease expiry exist to contain.
      Counted ``fleet.chaos_partitions``; the stop path always heals the
      link so a cancelled drill cannot leave a permanent partition.
    """

    def __init__(self, fleet: FleetSupervisor | None, *, seed: int = 0,
                 kill_after_s: float = 2.0,
                 kill_period_s: float = 0.0, sig: int = signal.SIGKILL,
                 mode: str = "kill", degrade_stop_ms: float = 150.0,
                 degrade_period_ms: float = 500.0, degrade_duration_s: float = 10.0,
                 netchaos_tier: NetChaosTier | None = None,
                 partition_fault: str = "blackhole"):
        if mode not in ("kill", "degrade", "partition"):
            raise ValueError(f"chaos mode must be kill|degrade|partition, got {mode!r}")
        if mode == "partition" and netchaos_tier is None:
            raise ValueError("partition chaos needs the serve.fleet.netchaos proxy tier")
        if mode in ("kill", "degrade") and fleet is None:
            raise ValueError(f"{mode} chaos needs a local supervisor (not --attach)")
        self._fleet = fleet
        self._tier = netchaos_tier
        self._partition_fault = partition_fault
        self._rng = random.Random(seed)
        self._kill_after_s = kill_after_s
        self._kill_period_s = kill_period_s
        self._sig = sig
        self._mode = mode
        self._degrade_stop_s = degrade_stop_ms / 1e3
        self._degrade_period_s = degrade_period_ms / 1e3
        self._degrade_duration_s = degrade_duration_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "FleetChaos":
        self._thread = threading.Thread(target=self._loop, name="fleet-chaos", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        try:  # YAMT011: silent chaos death = a drill that never ran
            if self._stop.wait(self._kill_after_s):
                return
            if self._mode == "degrade":
                self._degrade_once()
                return
            if self._mode == "partition":
                self._partition_once()
                return
            self._fleet.kill_replica(rng=self._rng, sig=self._sig)
            while self._kill_period_s > 0 and not self._stop.wait(self._kill_period_s):
                self._fleet.kill_replica(rng=self._rng, sig=self._sig)
        except Exception as e:  # noqa: BLE001 — contain, count, report
            obs_registry.get_registry().counter("serve.thread_crashes").inc()
            emit(f"[fleet] chaos thread crashed: {type(e).__name__}: {e}")

    def _degrade_once(self) -> None:
        slot = self._fleet.pick_live_slot(rng=self._rng)
        if slot is None:
            return
        obs_registry.get_registry().counter("fleet.chaos_degrades").inc()
        emit(f"[fleet] CHAOS: degrading replica r{slot} "
             f"(SIGSTOP {self._degrade_stop_s * 1e3:.0f}ms / "
             f"{self._degrade_period_s * 1e3:.0f}ms for {self._degrade_duration_s:.0f}s)")
        deadline = time.monotonic() + self._degrade_duration_s
        try:
            while time.monotonic() < deadline and not self._stop.is_set():
                if not self._fleet.signal_replica(slot, signal.SIGSTOP):
                    return  # the victim died (supervisor will respawn): drill over
                # a bounded freeze, then resume — stop() mid-pulse still
                # falls through to the finally's releasing SIGCONT
                self._stop.wait(self._degrade_stop_s)
                self._fleet.signal_replica(slot, signal.SIGCONT)
                self._stop.wait(self._degrade_period_s - self._degrade_stop_s)
        finally:
            self._fleet.signal_replica(slot, signal.SIGCONT)

    def _partition_once(self) -> None:
        proxy = self._tier.pick(rng=self._rng)
        if proxy is None:
            return
        obs_registry.get_registry().counter("fleet.chaos_partitions").inc()
        emit(f"[fleet] CHAOS: partitioning link to {proxy.upstream_host}:"
             f"{proxy.upstream_port} ({self._partition_fault} for "
             f"{self._degrade_duration_s:.0f}s)")
        proxy.set_fault(self._partition_fault)
        try:
            self._stop.wait(self._degrade_duration_s)
        finally:
            # the stop path always heals: a cancelled drill must not leave
            # a permanent partition behind
            proxy.set_fault(None)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def run(cfg: Config, replica_argv: list[str]) -> dict:
    """The fleet serving loop: supervisor + router + frontend + (optional)
    autoscaler + chaos, until SIGTERM/SIGINT. SIGHUP = rolling restart."""
    log = Logger(cfg.train.log_dir, enabled=True, tensorboard=False)
    reg = obs_registry.get_registry()
    if cfg.obs.histogram_buckets:
        reg.set_default_buckets(cfg.obs.histogram_buckets)
    reg.set_build_info(obs_device.build_info())  # no jax import: versions + git sha
    log.set_registry(reg)
    tracer = obs_trace.configure(enabled=bool(cfg.obs.trace), ring_size=cfg.obs.trace_ring_size,
                                 process_name="router")
    fc = cfg.serve.fleet
    fobs = fc.obs
    stop_event = threading.Event()
    rolling_event = threading.Event()

    def _on_signal(signum, frame):
        log.log(f"signal {signum}: stopping router, draining fleet")
        stop_event.set()

    def _on_hup(signum, frame):
        rolling_event.set()

    try:
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
        signal.signal(signal.SIGHUP, _on_hup)
    except ValueError:
        pass  # embedded (test) runs drive stop_event directly

    hedger = Hedger(
        quantile=fc.hedge.quantile, min_samples=fc.hedge.min_samples,
        min_timer_ms=fc.hedge.min_timer_ms, max_timer_ms=fc.hedge.max_timer_ms,
    ) if fc.hedge.enable else None
    router = Router(
        default_class=cfg.serve.admission.default_class,
        poll_interval_s=fc.poll_interval_s,
        eject_failures=fc.eject_failures,
        route_attempts=fc.route_attempts,
        client_timeout_s=fc.client_timeout_s,
        connect_timeout_s=fc.connect_timeout_s or None,
        eject_cooldown_s=fc.eject_cooldown_s,
        lease_ttl_s=fc.lease_ttl_s,
        hedger=hedger,
        poll_jitter=fc.poll_jitter,
        slow_eject=fc.slow_eject.enable,
        slow_factor=fc.slow_eject.slow_factor,
        slow_eject_after=fc.slow_eject.eject_after,
        slow_cooldown_s=fc.slow_eject.cooldown_s,
        slow_min_ms=fc.slow_eject.min_ms,
        lat_alpha=fc.slow_eject.lat_alpha,
    ).start()
    # fleet observability (obs/fleet.py): the incident flight recorder is
    # the router's event sink, and the federation scrape-merges every live
    # replica's /varz into fleet-level families on the supervisor loop
    recorder = None
    if fobs.flight_recorder and cfg.train.log_dir:
        recorder = FlightRecorder(
            cfg.train.log_dir,
            ring=fobs.recorder_ring,
            min_interval_s=fobs.recorder_min_interval_s,
            incident_level=fobs.incident_brownout_level,
        )
        router.set_event_sink(recorder.record)
    federation = None
    if fobs.federate:
        federation = FleetFederation(
            router.backends,
            slo=SLOTracker(
                target_p99_ms=fobs.slo_target_p99_ms,
                error_budget=fobs.slo_error_budget,
                short_window_s=fobs.slo_short_window_s,
                long_window_s=fobs.slo_long_window_s,
                fast_burn=fobs.slo_fast_burn,
            ),
            recorder=recorder,
            signal_classes=(cfg.serve.brownout.signal_class,),
            scrape_timeout_s=fobs.scrape_timeout_s,
        )
    # netchaos proxy tier (serve/netchaos.py): the router only ever speaks
    # to supervised replicas THROUGH their per-link fault proxies, so the
    # partition chaos mode (and the serve_bench partition rounds) can
    # blackhole/reset/flap one link without touching any process
    tier = None
    if fc.netchaos.enable:
        nc = fc.netchaos
        tier = NetChaosTier(
            seed=nc.seed, fault_rate=nc.fault_rate, latency_ms=nc.latency_ms,
            jitter_ms=nc.jitter_ms, bandwidth_kbps=nc.bandwidth_kbps,
            flap_period_s=nc.flap_period_s, flap_down_s=nc.flap_down_s,
        )
    # model-sharded placement (serve.zoo.placement): each fleet slot spawns
    # with its OWN serve.zoo.models subset (serve/zoo.py slot_overrides),
    # and the router learns which models each address serves so its pick
    # only routes a model to replicas that load it
    per_slot_argv: dict[int, list[str]] = {}
    slot_names: dict[int, tuple[str, ...]] = {}
    if cfg.serve.zoo.models:
        from ..serve import zoo as zoo_mod
        paths = zoo_mod.parse_models(cfg.serve.zoo.models)
        groups = zoo_mod.parse_placement(cfg.serve.zoo.placement, list(paths))
        for i in range(fc.replicas):
            per_slot_argv[i] = zoo_mod.slot_overrides(cfg.serve.zoo, i)
            slot_names[i] = zoo_mod.slot_models(groups, i)
        log.log("zoo placement: " + "; ".join(
            f"r{i}:{'|'.join(slot_names[i])}" for i in sorted(slot_names)))

    def _apply_placement() -> None:
        if not slot_names or fleet is None:
            return
        assignments = {}
        for r in fleet.replicas():
            if r["addr"] is not None and r["slot"] in slot_names:
                key = f"{r['addr']['host']}:{r['addr']['port']}"
                # digest '' = placement-only knowledge; a replica that ALSO
                # lease-registers overwrites with its stamped digests
                assignments[key] = {n: "" for n in slot_names[r["slot"]]}
        router.set_backend_models(assignments)

    def route_backends(addrs) -> None:
        router.set_backends(tier.route(addrs) if tier is not None else addrs)
        _apply_placement()
    # --attach (serve.fleet.attach): the router tier over EXTERNALLY-managed
    # replicas — no local spawn, no supervisor. This IS the multi-host
    # deployment shape, rehearsed on loopback: replicas live wherever they
    # live (other hosts, other supervisors), the attach list seeds the
    # static backend set, and late arrivals join via the /register lease.
    attach = [a.strip() for a in fc.attach.split(",") if a.strip()]
    fleet = None
    if attach:
        route_backends([tuple(a.rsplit(":", 1)) for a in attach])
    else:
        fleet = FleetSupervisor(
            replica_argv=replica_argv,
            log_dir=cfg.train.log_dir,
            replicas=fc.replicas,
            restart_backoff_ms=fc.restart_backoff_ms,
            restart_backoff_max_s=fc.restart_backoff_max_s,
            spawn_timeout_s=fc.spawn_timeout_s,
            drain_timeout_s=cfg.serve.drain_timeout_s + 10.0,
            per_slot_argv=per_slot_argv,
            on_change=route_backends,
            logger=log,
        )
    # confidence cascade (serve/cascade.py): the frontend consumes the
    # cascade TIER instead of the bare router — small model answers, low
    # top-1-margin answers re-submit to the big tier; membership/
    # registration/introspection delegate through to the router
    serving_tier = router
    if cfg.serve.zoo.cascade.enable:
        from ..serve.cascade import CascadeTier
        cc = cfg.serve.zoo.cascade
        serving_tier = CascadeTier(
            router, small=cc.small, big=cc.big, threshold=cc.threshold,
            respect_explicit_model=cc.respect_explicit_model,
        )
        log.log(f"cascade armed: {cc.small} -> {cc.big} "
                f"(escalate below margin {cc.threshold:.2f})")
    result: dict = {}
    frontend = autoscaler = chaos = brownout = watchdog = None
    try:
        if fleet is not None:
            fleet.start()
        frontend = Frontend(
            serving_tier,
            host=cfg.serve.listen.host,
            port=cfg.serve.listen.port,
            request_timeout_s=cfg.serve.listen.request_timeout_s,
            replica_id=cfg.serve.listen.replica_id or "router",
            federation=federation,
        ).start()
        n_replicas = fleet.n_replicas if fleet is not None else len(attach)
        addr = {"host": cfg.serve.listen.host, "port": frontend.port, "pid": os.getpid(),
                "replica_id": frontend.replica_id, "role": "router",
                "replicas": n_replicas, "attach": attach}
        if cfg.train.log_dir:
            write_listen_addr(cfg.train.log_dir, addr)
        log.log(f"fleet of {n_replicas} {'attached' if attach else 'spawned'} "
                f"replicas behind {frontend.url} (hedge={'on' if hedger else 'off'}, "
                f"lease ttl {fc.lease_ttl_s:.0f}s)")
        if fc.autoscale.enable and fleet is None:
            log.log("autoscaler disabled: --attach mode has no supervisor to scale")
        if fc.autoscale.enable and fleet is not None:
            a = fc.autoscale
            autoscaler = Autoscaler(
                fleet, router,
                min_replicas=a.min_replicas, max_replicas=a.max_replicas,
                interval_s=a.interval_s, cooldown_s=a.cooldown_s,
                up_p99_ms=a.up_p99_ms, down_p99_ms=a.down_p99_ms,
                up_queue_depth=a.up_queue_depth, down_queue_depth=a.down_queue_depth,
                signal_class=a.signal_class,
            ).start()
        if cfg.serve.brownout.enable:
            # brownout at the ROUTER tier: signals from the fleet-side
            # latency family + routable backlog; actuates hedging (L1) and
            # fleet-door class shedding (L3+). Replica-tier batcher/
            # admission degradation rides each replica's own controller
            # (cli/serve.py) off the same config block.
            brownout = BrownoutController.from_config(
                cfg.serve.brownout,
                SignalReader(
                    latency_family=ROUTER_LATENCY,
                    signal_class=cfg.serve.brownout.signal_class,
                    queue_depth_fn=router.mean_queue_depth,
                ),
                # the flight recorder is a brownout TARGET too: level
                # transitions land in the event ring, and climbing to
                # incident_brownout_level arms an incident dump
                targets=(router,) + ((recorder,) if recorder is not None else ()),
            ).start()
            log.log(f"brownout ladder armed at the router tier "
                    f"(L0..L{cfg.serve.brownout.max_level})")
        if fc.chaos.enable:
            chaos = FleetChaos(
                fleet, seed=fc.chaos.seed, kill_after_s=fc.chaos.kill_after_s,
                kill_period_s=fc.chaos.kill_period_s,
                sig=signal.SIGKILL if fc.chaos.signal == "kill" else signal.SIGTERM,
                mode=fc.chaos.mode,
                degrade_stop_ms=fc.chaos.degrade_stop_ms,
                degrade_period_ms=fc.chaos.degrade_period_ms,
                degrade_duration_s=fc.chaos.degrade_duration_s,
                netchaos_tier=tier,
                partition_fault=fc.netchaos.fault,
            ).start()
            log.log(f"CHAOS: replica {fc.chaos.mode} on (seed={fc.chaos.seed}, "
                    f"after={fc.chaos.kill_after_s}s, period={fc.chaos.kill_period_s}s)")
        # fleet-tier stall watchdog: the supervisor loop heartbeats every
        # tick, so a wedged ROUTER process dumps a hang report that names
        # the fleet's state — replica table (weights/ejection), lease ages,
        # brownout level, and the oldest in-flight router request
        if cfg.obs.watchdog_deadline_s > 0 and cfg.train.log_dir:
            watchdog = StallWatchdog(
                cfg.train.log_dir,
                cfg.obs.watchdog_deadline_s,
                tracer=tracer,
                registry=reg,
                poll_s=cfg.obs.watchdog_poll_s,
                logger=log,
            )
            watchdog.register_info("fleet", lambda: {
                "replicas": router.replicas_state(),
                "lease_ages_s": router.lease_ages(),
                "brownout_level": int(reg.gauge("serve.brownout_level").value),
                "oldest_request": router.oldest_inflight(),
            })
            if federation is not None:
                watchdog.register_info("federation", federation.snapshot)
            watchdog.start()
        # federation cadence: its own interval, or ride the router's poll
        scrape_every = fobs.scrape_interval_s or fc.poll_interval_s
        next_scrape = time.monotonic()
        while not stop_event.wait(0.2):
            if watchdog is not None:
                watchdog.arm(phase="serve")
            now = time.monotonic()
            if federation is not None and now >= next_scrape:
                next_scrape = now + scrape_every
                federation.scrape_once()
            if recorder is not None:
                incident = recorder.maybe_dump(federation)
                if incident:
                    log.log(f"INCIDENT dumped: {incident}")
            if rolling_event.is_set():
                rolling_event.clear()
                if fleet is None:
                    log.log("SIGHUP ignored: --attach replicas are externally managed")
                    continue
                log.log("SIGHUP: rolling restart")
                n = fleet.rolling_restart()
                log.log(f"rolling restart complete: {n} replicas recycled")
        result.update({"listened": True, **addr})
    finally:
        t0 = time.perf_counter()
        if recorder is not None:
            # an armed trigger must not be lost to shutdown: one last dump
            # attempt with the latest federated view, then tear down
            recorder.maybe_dump(federation)
        if watchdog is not None:
            watchdog.stop()
        if chaos is not None:
            chaos.stop()
        if brownout is not None:
            brownout.stop()
            result["brownout_trace"] = brownout.trace
        if autoscaler is not None:
            autoscaler.stop()
            result["autoscale_trace"] = autoscaler.trace
        if frontend is not None:
            frontend.stop()
        router.stop()
        if tier is not None:
            tier.stop()
        if fleet is not None:
            fleet.stop()
        result["drain_s"] = round(time.perf_counter() - t0, 3)
        log.log(f"fleet drained in {result['drain_s']:.2f}s")
        if cfg.train.log_dir:
            if tracer.enabled:
                tracer.write(os.path.join(cfg.train.log_dir, "obs_trace.json"))
            os.makedirs(cfg.train.log_dir, exist_ok=True)
            with open(os.path.join(cfg.train.log_dir, "obs_registry.json"), "w") as f:
                json.dump(reg.snapshot(), f, indent=1, sort_keys=True)
        log.close()
    return result


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # replicas re-parse the SAME operator argv (app: + overrides) plus their
    # per-slot overrides, so fleet config and replica config cannot drift;
    # --listen sugar is meaningless here (the fleet always listens).
    # `--attach host:port,...` is sugar for serve.fleet.attach=... — the
    # router tier over externally-started replicas, no local spawn.
    argv = [a for a in argv if a != "--listen"]
    cleaned: list[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--attach":
            if i + 1 >= len(argv):
                raise ValueError("--attach needs a host:port[,host:port...] value")
            cleaned.append(f"serve.fleet.attach={argv[i + 1]}")
            i += 2
            continue
        if a.startswith("--attach="):
            cleaned.append(f"serve.fleet.attach={a.split('=', 1)[1]}")
            i += 1
            continue
        cleaned.append(a)
        i += 1
    cfg = parse_cli(cleaned)
    if not cfg.serve.fleet.attach and not (
            cfg.serve.bundle or cfg.serve.export_from or cfg.serve.zoo.models):
        # attach mode spawns nothing: the remote replicas own their bundles
        raise ValueError("fleet: needs serve.bundle or serve.zoo.models (replicas "
                         "load them at spawn) or --attach host:port,...")
    return run(cfg, cleaned)


if __name__ == "__main__":
    main()
