"""Model profiling CLI (reference: utils/model_profiling.py's printed
summary, SURVEY.md §2 #10):

  python -m yet_another_mobilenet_series_tpu.cli.profile app:apps/<x>.yml
  python -m yet_another_mobilenet_series_tpu.cli.profile model.arch=mnasnet_a1

Prints the per-layer MACs/params table, totals, and (for supernets) the
per-block atom-cost distribution that weights the AtomNAS penalty.
"""

from __future__ import annotations

import sys

import numpy as np

from ..config import parse_cli
from ..models import get_model
from ..utils.profiling import profile_network


def main(argv=None):
    cfg = parse_cli(sys.argv[1:] if argv is None else argv)
    net = get_model(cfg.model, cfg.data.image_size)
    prof = profile_network(net)
    name = cfg.model.network_spec or f"{cfg.model.arch} x{cfg.model.width_mult}"
    print(f"# {name} @ {cfg.data.image_size}x{cfg.data.image_size}")
    print(prof.summary())
    print(f"\ntotal: {prof.total_macs/1e6:.1f}M MACs, {prof.total_params/1e6:.3f}M params")
    multi_kernel = [i for i, b in enumerate(net.blocks) if len(b.kernel_sizes) > 1]
    if multi_kernel:
        print("\natom cost table (per-block min/mean/max MACs per atom):")
        for i in multi_kernel:
            c = prof.atom_costs[i]
            print(f"  block{i:<3} atoms={c.size:<5} cost {c.min():>10.0f} / {np.mean(c):>10.0f} / {c.max():>10.0f}")


if __name__ == "__main__":
    main()
