"""Training/eval entry point — the reference train.py rebuilt for TPU
(SURVEY.md §3.1): ``python -m yet_another_mobilenet_series_tpu.cli.train
app:<yaml> [key=value ...]``.

Owns the epoch/step loops, validation on EMA shadow weights, checkpoint
save/resume (pruned-shape-first), the AtomNAS shrink schedule (in-jit mask
refresh at fine cadence + physical rematerialization at coarse cadence), and
throughput/accuracy logging. Everything inside the step is one compiled XLA
program (train/steps.py + parallel/dp.py).

Runtime telemetry (obs/, docs/OBSERVABILITY.md) wraps the loop without
touching the compiled step: spans time every host-side phase (data fetch,
dispatch, syncs, prune, eval, checkpoint, rebuilds), the metrics registry
rides into every scalars row, and the stall watchdog turns a wedged tunnel
into a hang_report.json instead of a silent death.

Survivability (the training-side robustness layer, README "Preemption &
resume"): SIGTERM/SIGINT triggers a final SYNCHRONOUS checkpoint and a
clean exit with a resume marker instead of losing the epoch; restore walks
back through older checkpoints when the latest is corrupt or half-written
(digest-verified, ckpt/manager.py); train.guard skips-and-rolls-back
bounded non-finite steps (train/guard.py); the data stream skips corrupt
records with bounded abort (data/pipeline.py); and train.faults injects all
of the above deterministically (train/faults.py, scripts/train_chaos.py).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

import jax
import numpy as np

from ..ckpt.manager import CheckpointCorrupt, CheckpointManager
from ..config import Config, parse_cli
from .. import data as data_lib
from ..models import get_model
from ..models.specs import Network
from ..nas import masking, penalty, rematerialize
from ..obs import device as obs_device
from ..obs import registry as obs_registry
from ..obs import trace as obs_trace
from ..obs.watchdog import StallWatchdog
from ..parallel import dp, mesh as mesh_lib
from ..train import optim, schedules, steps
from ..utils.cadence import StepCadence
from ..utils.logging import Logger
from ..utils.meters import MetricLogger, format_metrics
from ..utils.profiling import profile_network


# written next to the checkpoint on a clean preemption exit; consumed (and
# removed) by the next resumed run. Schedulers/operators can poll it to tell
# "checkpointed and exited on purpose" from "died".
PREEMPT_MARKER_NAME = "preempt_marker.json"


def _dataset_sizes(cfg: Config) -> tuple[int, int]:
    if cfg.data.dataset == "fake":
        return cfg.data.fake_train_size, cfg.data.fake_eval_size
    return cfg.data.num_train_examples, cfg.data.num_eval_examples


class Trainer:
    """Builds and owns all step functions; rebuilt wholesale on
    rematerialization (shapes changed => everything re-jits)."""

    def __init__(self, cfg: Config, net: Network, mesh, log: Logger):
        self.cfg = cfg
        self.net = net
        self.mesh = mesh
        self.log = log
        n_train, _ = _dataset_sizes(cfg)
        self.steps_per_epoch = max(n_train // cfg.train.batch_size, 1)
        self.lr_fn = schedules.make_lr_schedule(
            cfg.schedule, cfg.train.batch_size, self.steps_per_epoch, cfg.train.epochs
        )
        self.params_example, _ = jax.eval_shape(lambda: net.init(jax.random.PRNGKey(0)))
        self.optimizer = optim.make_optimizer(
            cfg.optim, self.lr_fn, self.params_example,
            shard_axis=mesh_lib.DATA_AXIS if cfg.dist.shard_optimizer else None,
        )
        self.penalty_fn = (
            penalty.make_penalty_fn(net, cfg.prune, self.steps_per_epoch) if cfg.prune.enable else None
        )
        self.train_step = dp.make_dp_train_step(
            net, cfg, self.optimizer, self.lr_fn, mesh,
            penalty_fn=self.penalty_fn, params_example=self.params_example,
            clip_shard_aware=cfg.dist.shard_optimizer,  # optimizer built with shard_axis above
        )
        self.eval_step = dp.make_dp_eval_step(net, cfg, mesh)
        # the complete per-cadence prune event (reached check + adaptive rho
        # + mask update) as ONE device program — shared verbatim between the
        # single-step dispatch path and the grouped program, so
        # steps_per_dispatch>1 no longer has to be forced off under pruning
        self.prune_stop_step = int(cfg.prune.stop_epoch_frac * cfg.train.epochs * self.steps_per_epoch)
        self.prune_event = (
            jax.jit(masking.make_prune_event(net, cfg.prune, self.prune_stop_step))
            if cfg.prune.enable else None
        )
        self.sync_check = dp.make_replica_sync_check(mesh)
        if cfg.dist.shard_optimizer:
            from ..parallel import zero

            # jitted ONCE: a fresh jax.jit per checkpoint would retrace the
            # full gather program every save
            self._gather_opt = jax.jit(zero.gather_opt_state)

    def init_state(self, rng) -> steps.TrainState:
        zero_opt = self.cfg.dist.shard_optimizer
        ts = steps.init_train_state(self.net, self.cfg, self.optimizer, rng, with_opt=not zero_opt)
        if self.cfg.prune.enable:
            ts = ts.replace(masks=masking.init_masks(self.net))
        ts = mesh_lib.replicate(ts, self.mesh)
        if zero_opt:
            from ..parallel import zero

            ts = ts.replace(opt_state=zero.init_opt_state(self.optimizer, ts.params, self.mesh))
        return ts

    def abstract_state(self) -> steps.TrainState:
        """Shape/dtype skeleton of the CHECKPOINT format (ckpt phase 2).

        Checkpoints always carry the optimizer state params-shaped and
        replicated — even under ZeRO — so they are portable across chip
        counts (train on 8 chips, resume on 256) and multi-host saves never
        need a cross-host device_get. The flat sharded form exists only
        inside the live mesh (parallel/zero.py)."""

        def build():
            ts = steps.init_train_state(self.net, self.cfg, self.optimizer, jax.random.PRNGKey(0))
            if self.cfg.prune.enable:
                ts = ts.replace(masks=masking.init_masks(self.net))
            return ts

        return jax.eval_shape(build)

    def place_state(self, ts: steps.TrainState) -> steps.TrainState:
        """Puts a checkpoint-format TrainState onto the mesh: everything
        replicated; under ZeRO the params-shaped optimizer state is scattered
        to this mesh's flat shards (any chip count)."""
        if self.cfg.dist.shard_optimizer:
            from ..parallel import zero

            opt = ts.opt_state
            ts = mesh_lib.replicate(ts.replace(opt_state=None), self.mesh)
            return ts.replace(opt_state=zero.scatter_opt_state(opt, ts.params, self.mesh))
        return mesh_lib.replicate(ts, self.mesh)

    def checkpoint_view(self, ts: steps.TrainState) -> steps.TrainState:
        """Converts a live TrainState to the checkpoint format (gathers the
        ZeRO flat shards back to params-shaped; identity otherwise)."""
        if self.cfg.dist.shard_optimizer:
            return ts.replace(opt_state=self._gather_opt(ts.opt_state, ts.params))
        return ts


class _Preemption:
    """SIGTERM/SIGINT -> cooperative stop flag. The loop checks ``requested``
    at step boundaries and exits through the final-synchronous-checkpoint
    path (a preemption loses at most the in-flight step, not the epoch).

    Handlers install only in the main thread (embedded/test runs keep their
    own); the previous handlers are restored on uninstall so an in-process
    caller (pytest) is left untouched. Multi-host note: the scheduler
    delivers the signal to every host and the loops run in lockstep, so all
    hosts reach the same collective save — the same assumption Orbax's own
    preemption handling makes."""

    def __init__(self, log: Logger):
        self._log = log
        self.requested = False
        self.reason = ""
        self._prev: dict = {}

    def _handle(self, signum, frame):
        self.requested = True
        self.reason = signal.Signals(signum).name
        self._log.log(f"{self.reason} received: will checkpoint and exit at the "
                      "next step boundary")

    def install(self) -> "_Preemption":
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev[sig] = signal.signal(sig, self._handle)
            except ValueError:
                break  # not the main thread: cooperative flag only
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass  # uninstall from a non-main thread: nothing was installed
        self._prev.clear()


def _restore_tree(ckpt: CheckpointManager, step: int, abstract: dict, log: Logger):
    """restore_tree with the NARROW legacy-rho_mult retry: the old bare
    ``except Exception`` retried EVERY failure as a legacy checkpoint, which
    masked genuine corruption as a shape quirk. Now the retry happens only
    when the saved tree demonstrably lacks the rho_mult item (or its
    metadata is unreadable — the pre-metadata behavior, kept for old saves);
    digest mismatches and failures of a checkpoint that HAS the item
    propagate to the fallback walk with their cause logged."""
    import jax.numpy as jnp

    try:
        return ckpt.restore_tree(step, abstract)
    except CheckpointCorrupt:
        raise  # verified corruption is never a legacy-layout quirk
    except Exception as e:  # noqa: BLE001 — orbax raises bare ValueError
        if "rho_mult" not in abstract or abstract["rho_mult"] is None:
            raise
        saved = ckpt.tree_keys(step)
        if saved is not None and "rho_mult" in saved:
            # the item exists on disk: this failure is corruption or a real
            # shape mismatch, not the pre-rho_mult layout
            log.log(f"restore at step {step} failed ({type(e).__name__}: {e}); "
                    "saved tree HAS rho_mult, so this is not a legacy checkpoint")
            raise
        # legacy checkpoint written before TrainState grew rho_mult: restore
        # without it and inject the neutral multiplier
        log.log(f"restore with rho_mult failed ({type(e).__name__}); retrying as legacy checkpoint")
        tree = ckpt.restore_tree(step, {k: v for k, v in abstract.items() if k != "rho_mult"})
        tree["rho_mult"] = jnp.ones((), jnp.float32)
        return tree


def _restore(ckpt: CheckpointManager, cfg: Config, mesh, log: Logger):
    """Two-phase resume (SURVEY.md §3.5): spec -> rebuild at pruned shape ->
    weights. Returns (trainer, ts, extra) or None when no checkpoint exists.

    Crash-consistent: candidates are tried NEWEST FIRST and a step whose
    spec sidecar is unreadable, whose tree fails to restore, or whose bytes
    fail digest verification (ckpt/manager.py) is logged, counted
    (``ckpt.restore_fallbacks``), and SKIPPED in favor of the previous step
    — a preemption mid-save costs one checkpoint interval, not the run.
    Raises only when checkpoints exist but none restores."""
    candidates = ckpt.all_steps()
    if not candidates:
        return None
    last_err = None
    for i, step in enumerate(candidates):
        if i:
            obs_registry.get_registry().counter("ckpt.restore_fallbacks").inc()
            log.log(f"falling back to checkpoint step {step}")
        try:
            spec = ckpt.restore_spec(step)
        except Exception as e:  # noqa: BLE001 — a torn sidecar must not end resume
            log.log(f"checkpoint step {step}: spec sidecar unreadable "
                    f"({type(e).__name__}: {e})")
            last_err = e
            continue
        _, net, extra = spec
        trainer = Trainer(cfg, net, mesh, log)
        abstract = steps.train_state_to_dict(trainer.abstract_state())
        try:
            tree = _restore_tree(ckpt, step, abstract, log)
        except Exception as e:  # noqa: BLE001 — corrupt tree: walk back one step
            log.log(f"checkpoint step {step}: tree restore failed "
                    f"({type(e).__name__}: {e})")
            last_err = e
            continue
        ts = trainer.place_state(steps.TrainState(**tree))
        return trainer, ts, extra
    raise RuntimeError(
        f"no restorable checkpoint: all {len(candidates)} candidate step(s) "
        f"{candidates} failed — see the per-step causes above"
    ) from last_err


def evaluate(trainer: Trainer, ts: steps.TrainState, cfg: Config, *, use_ema=True,
             watchdog: StallWatchdog | None = None) -> dict:
    """Validation pass on the EMA shadow weights (reference: eval-on-shadow,
    SURVEY.md §2 #8); falls back to the live weights when EMA is off.

    ONE host sync per pass: per-batch metrics accumulate as lazy device
    arrays (the eval_step outputs stay un-read, so dispatch keeps running
    ahead) and a single device_get lands at the end — the previous
    per-batch ``float(m[k])`` forced four host round-trips every step."""
    tracer = obs_trace.get_tracer()
    params = ts.ema_params if (use_ema and cfg.ema.enable) else ts.params
    state = ts.ema_state if (use_ema and cfg.ema.enable) else ts.state
    # eval_batch_size is GLOBAL (matching train's batch_size semantics):
    # round up to device divisibility, then give each host its share —
    # per-device eval memory stays constant as host count grows (padding
    # rows carry label=-1 and are masked out of every count)
    n_dev = trainer.mesh.size
    per_device = -(-cfg.train.eval_batch_size // n_dev)
    local_eval = per_device * (n_dev // jax.process_count())
    batches = data_lib.make_eval_source(cfg.data, local_eval, jax.process_index(), jax.process_count())
    totals = None
    with tracer.span("eval/pass", "eval"):
        for batch in batches:
            with tracer.span("eval/batch", "eval"):
                b = mesh_lib.shard_batch(batch, trainer.mesh)
                m = trainer.eval_step(params, state, b, ts.masks)
            totals = m if totals is None else jax.tree.map(lambda a, b_: a + b_, totals, m)
            if watchdog is not None:
                watchdog.arm(phase="eval")
        with tracer.span("sync/eval_gather", "sync"):
            host = (
                jax.device_get(totals) if totals is not None
                else {"top1": 0.0, "top5": 0.0, "n": 0.0, "loss_sum": 0.0}
            )
    obs_registry.get_registry().counter("eval.passes").inc()
    n = max(float(host["n"]), 1.0)
    return {
        "top1": float(host["top1"]) / n, "top5": float(host["top5"]) / n,
        "loss": float(host["loss_sum"]) / n, "n": int(float(host["n"])),
    }


def _maybe_rematerialize(trainer: Trainer, ts: steps.TrainState, log: Logger):
    """Physical shrink at coarse cadence (SURVEY.md §3.2 TPU translation).
    Returns (trainer, ts) — possibly rebuilt."""
    cfg = trainer.cfg
    summary = masking.mask_summary(trainer.net, ts.masks)
    if summary["alive_atoms"] == summary["total_atoms"]:
        return trainer, ts  # nothing died; skip the recompile
    # checkpoint_view: remat's channel slicers need the optimizer state in
    # params shape, not ZeRO's flat shards
    host_ts = jax.device_get(trainer.checkpoint_view(ts))
    masks = {k: np.asarray(v) for k, v in host_ts.masks.items()}
    new_net, new_p, new_s, new_masks, extras, report = rematerialize.rematerialize(
        trainer.net, host_ts.params, host_ts.state, masks,
        opt_state=host_ts.opt_state, ema_params=host_ts.ema_params, ema_state=host_ts.ema_state,
    )
    log.log(
        f"rematerialize: atoms {report.atoms_before}->{report.atoms_after}, "
        f"dropped blocks {report.dropped_blocks}, "
        f"MACs {profile_network(trainer.net).total_macs/1e6:.1f}M->{profile_network(new_net).total_macs/1e6:.1f}M"
    )
    new_trainer = Trainer(cfg, new_net, trainer.mesh, log)
    new_ts = steps.TrainState(
        step=host_ts.step, params=new_p, state=new_s, opt_state=extras["opt_state"],
        ema_params=extras.get("ema_params"), ema_state=extras.get("ema_state"), masks=new_masks,
        rho_mult=host_ts.rho_mult,
    )
    return new_trainer, new_trainer.place_state(new_ts)


def _init_or_warm_start(cfg: Config, net: Network, mesh, log: Logger, rng):
    """Fresh TrainState — or, when train.pretrained / train.torch_pretrained
    is set on a non-resumed training run, a warm start: weights (+ BN stats,
    + masks for a pruned source) from the source checkpoint, with a FRESH
    optimizer/step/EMA-shadow (finetune semantics — the reference's
    pretrained-init path, SURVEY.md §3.3)."""
    if cfg.train.torch_pretrained:
        from ..ckpt.torch_import import load_torch_checkpoint

        import jax.numpy as jnp

        params, state = load_torch_checkpoint(cfg.train.torch_pretrained, net)
        trainer = Trainer(cfg, net, mesh, log)
        ts = trainer.init_state(rng)
        rep = lambda t: mesh_lib.replicate(t, mesh)  # noqa: E731
        # EMA shadow must be a real copy, never an alias of the live buffers
        # (aliasing breaks donation of the TrainState)
        ts = ts.replace(
            params=rep(params), state=rep(state),
            ema_params=rep(jax.tree.map(jnp.copy, params)) if cfg.ema.enable else None,
            ema_state=rep(jax.tree.map(jnp.copy, state)) if cfg.ema.enable else None,
        )
        log.log(f"warm start from torch checkpoint {cfg.train.torch_pretrained}")
        return trainer, ts
    if cfg.train.pretrained:
        import jax.numpy as jnp

        mgr = CheckpointManager(cfg.train.pretrained, barrier_prefix="warmstart")
        src = _restore(mgr, cfg, mesh, log)
        mgr.close()
        if src is None:
            raise FileNotFoundError(f"train.pretrained={cfg.train.pretrained!r} holds no checkpoint")
        trainer, src_ts, _ = src  # trainer is built on the source's (possibly pruned) net
        ts = trainer.init_state(rng)
        copy = lambda t: jax.tree.map(jnp.copy, t)  # noqa: E731
        ts = ts.replace(
            params=src_ts.params, state=src_ts.state, masks=src_ts.masks,
            ema_params=copy(src_ts.params) if cfg.ema.enable else None,
            ema_state=copy(src_ts.state) if cfg.ema.enable else None,
        )
        log.log(f"warm start from checkpoint {cfg.train.pretrained} (step {int(src_ts.step)} weights, fresh optimizer)")
        return trainer, ts
    trainer = Trainer(cfg, net, mesh, log)
    return trainer, trainer.init_state(rng)


def run(cfg: Config) -> dict:
    import dataclasses as dc

    tuning_lines: list[str] = []
    if cfg.train.tuning_file:
        # before ANY backend touch (jax.distributed / make_mesh): a 'flags'
        # entry lands in XLA_FLAGS/LIBTPU_INIT_ARGS, read once at backend
        # init. Malformed file = hard error: the user explicitly pointed the
        # run at it (unlike bench.py, where tuning is an aux artifact).
        from ..train import tuning as tuning_lib

        cfg, tuning_lines = tuning_lib.apply_tuning_file(cfg)
    if cfg.dist.multihost:
        # multi-host rendezvous: the reference's torch.distributed env://
        # init; on TPU pods the coordinator/process env is auto-discovered.
        jax.distributed.initialize()
    if cfg.data.dataset == "fake" and cfg.data.fake_num_classes is None:
        cfg = dc.replace(cfg, data=dc.replace(cfg.data, fake_num_classes=cfg.model.num_classes))
    is_coord = mesh_lib.is_coordinator()
    log = Logger(cfg.train.log_dir, enabled=is_coord, tensorboard=bool(cfg.train.log_dir))
    mesh = mesh_lib.make_mesh(cfg.dist.num_devices)
    log.log(f"devices: {mesh.size} ({jax.devices()[0].platform}), hosts: {jax.process_count()}")
    for line in tuning_lines:  # provenance of measured-winner overrides
        log.log(line)

    # ---- runtime telemetry (obs/, docs/OBSERVABILITY.md) ----
    # registry snapshots ride into every scalars row; the span tracer and
    # stall watchdog are coordinator-only opt-ins (cfg.obs)
    reg = obs_registry.get_registry()
    if cfg.obs.histogram_buckets:
        # before any training histogram exists: the ladder applies at creation
        reg.set_default_buckets(cfg.obs.histogram_buckets)
    # device telemetry (obs/device.py): version attribution + HBM/RSS pull
    # gauges — read only when a snapshot is taken (the log cadence), so they
    # ride every scalars row, hang report, and train_health dump for free
    reg.set_build_info(obs_device.build_info())
    obs_device.install_memory_gauges(reg)
    log.set_registry(reg)
    tracer = obs_trace.configure(
        enabled=bool(cfg.obs.trace) and is_coord, ring_size=cfg.obs.trace_ring_size
    )
    watchdog: StallWatchdog | None = None
    if cfg.obs.watchdog_deadline_s > 0 and is_coord and cfg.train.log_dir:
        watchdog = StallWatchdog(
            cfg.train.log_dir, cfg.obs.watchdog_deadline_s, tracer=tracer, registry=reg,
            poll_s=cfg.obs.watchdog_poll_s, logger=log,
        )
        watchdog.start()

    try:
        return _run_impl(cfg, log, mesh, is_coord, tracer, watchdog)
    finally:
        # flush telemetry on EVERY exit — a crash mid-epoch is exactly when
        # the trace and counters matter most
        if watchdog is not None:
            watchdog.stop()
        if tracer.enabled and cfg.train.log_dir and is_coord:
            path = tracer.write(os.path.join(cfg.train.log_dir, "obs_trace.json"))
            log.log(f"span trace -> {path} (open in ui.perfetto.dev or chrome://tracing)")
        if is_coord and cfg.train.log_dir:
            snap_path = os.path.join(cfg.train.log_dir, "obs_registry.json")
            with open(snap_path, "w") as f:
                json.dump(reg.snapshot(), f, indent=1, sort_keys=True)
        log.close()


def _run_impl(cfg: Config, log: Logger, mesh, is_coord: bool, tracer, watchdog) -> dict:
    net = get_model(cfg.model, cfg.data.image_size)
    prof = profile_network(net)
    arch_name = cfg.model.network_spec or f"{cfg.model.arch} x{cfg.model.width_mult}"
    log.log(f"model {arch_name}: {prof.total_params/1e6:.2f}M params, {prof.total_macs/1e6:.1f}M MACs")
    reg = obs_registry.get_registry()

    ckpt = CheckpointManager(
        cfg.train.log_dir + "/ckpt", max_to_keep=cfg.train.max_checkpoints,
        barrier_prefix="periodic",
    )
    # the best-checkpoint manager is created lazily on the first new-best
    # eval, inside _train_or_eval; the shared box lets the finally below see
    # it on every exit path
    best_box: list[CheckpointManager] = []
    try:
        return _train_or_eval(cfg, net, log, mesh, is_coord, tracer, watchdog, ckpt, best_box)
    finally:
        # EVERY exit path — normal, KeyboardInterrupt, any raise — waits for
        # in-flight async saves BEFORE closing, so a checkpoint is never
        # abandoned half-written (the crash-consistency contract resume
        # relies on); a failed wait is logged, never allowed to mask the
        # original exception
        for mgr in (best_box[0] if best_box else None, ckpt):
            if mgr is None:
                continue
            try:
                mgr.wait()
            except Exception as e:  # noqa: BLE001 — shutdown must reach close()
                log.log(f"checkpoint wait on shutdown failed ({type(e).__name__}: {e})")
            try:
                mgr.close()
            except Exception as e:  # noqa: BLE001 — best-effort shutdown
                log.log(f"checkpoint close on shutdown failed ({type(e).__name__}: {e})")


def _record_step_cost(trainer: Trainer, ts, batch, rng, reg, tracer, log: Logger,
                      first_dispatch_s: float) -> None:
    """Device-cost accounting for the compiled train step (obs/device.py):
    the first dispatch's host wall time (≈ trace + compile under async
    dispatch — the run never blocks on device execution here) lands in
    ``obs.compile_seconds``, and a one-time re-lower of the step records its
    cost_analysis FLOPs/bytes into the ``train_step`` cost gauges. Lowering
    traces but does NOT compile, so the one-off cost is seconds of host
    time per trainer build — amortized to noise over a run. Telemetry only:
    any failure is logged and swallowed, never fatal."""
    reg.histogram("obs.compile_seconds").observe(first_dispatch_s)
    reg.counter("obs.compiles").inc()
    try:
        with tracer.span("dispatch/cost_analysis", "dispatch"):
            lowered = trainer.train_step.lower(ts, batch, rng)
        cost = obs_device.record_cost(
            "train_step", lowered, compile_seconds=first_dispatch_s, registry=reg)
    except Exception as e:  # noqa: BLE001 — cost telemetry must never end a run
        log.log(f"train step cost_analysis unavailable ({type(e).__name__}: {e})")
        return
    if cost.get("flops"):
        log.log(
            f"train step cost_analysis: {cost['flops'] / 1e9:.3f} GFLOP, "
            f"{cost.get('bytes', 0) / 1e6:.1f} MB accessed per step "
            f"(first dispatch {first_dispatch_s:.1f}s ≈ trace+compile)"
        )


def _train_or_eval(cfg: Config, net: Network, log: Logger, mesh, is_coord: bool, tracer,
                   watchdog, ckpt: CheckpointManager, best_box: list) -> dict:
    # ---- eval-only path (acceptance config #1) ----
    if cfg.train.test_only:
        if cfg.train.torch_pretrained:
            # real pretrained torch weights — the "proves the model grammar
            # against real weights" milestone (SURVEY.md §7 stage 2); shares
            # the warm-start import path (EMA shadow = imported weights)
            trainer, ts = _init_or_warm_start(cfg, net, mesh, log, jax.random.PRNGKey(cfg.train.seed))
        else:
            src = cfg.train.pretrained or cfg.train.log_dir + "/ckpt"
            mgr = CheckpointManager(src, barrier_prefix="restore") if cfg.train.pretrained else ckpt
            restored = _restore(mgr, cfg, mesh, log)
            if mgr is not ckpt:
                mgr.close()
            if restored is None:
                log.log("no checkpoint found; evaluating fresh init (smoke mode)")
                trainer = Trainer(cfg, net, mesh, log)
                ts = trainer.init_state(jax.random.PRNGKey(cfg.train.seed))
            else:
                trainer, ts, _ = restored
        result = evaluate(trainer, ts, cfg, watchdog=watchdog)
        log.log(format_metrics("eval:", result))
        return result

    # ---- training path ----
    reg = obs_registry.get_registry()
    rng = jax.random.PRNGKey(cfg.train.seed)
    restored = _restore(ckpt, cfg, mesh, log) if cfg.train.resume else None
    start_epoch = 0.0
    if restored is not None:
        trainer, ts, extra = restored
        start_epoch = float(extra.get("epoch", int(ts.step) / trainer.steps_per_epoch))
        log.log(f"resumed at step {int(ts.step)} (epoch {start_epoch:.2f})")
        marker = os.path.join(cfg.train.log_dir, PREEMPT_MARKER_NAME)
        if is_coord and os.path.exists(marker):
            # the marker's job (tell the scheduler/operator a clean resume
            # point exists) is done once the resume actually happened
            os.remove(marker)
            log.log("preemption resume marker consumed")
    else:
        log.mark_fresh_run()  # truncate metrics.jsonl: steps restart at 0
        trainer, ts = _init_or_warm_start(cfg, net, mesh, log, rng)

    start_step = int(ts.step)
    local_batch = mesh_lib.local_batch_slice(cfg.train.batch_size, mesh)
    if cfg.train.faults.enable:
        # seeded train-side chaos (train/faults.py): wraps the RAW stream so
        # injected corrupt records travel the real resilience path
        from ..train.faults import FaultyTrainSource

        train_src = data_lib.make_train_source(
            cfg.data, local_batch, cfg.train.seed, jax.process_index(), jax.process_count(),
            start_step=start_step,
            inject=lambda it: FaultyTrainSource.from_config(it, cfg.train.faults,
                                                            start_step=start_step),
        )
    else:
        train_src = data_lib.make_train_source(
            cfg.data, local_batch, cfg.train.seed, jax.process_index(), jax.process_count(),
            # resume continues the data order at the restored step (each
            # global step consumed exactly one local batch per host)
            start_step=start_step,
        )
    train_iter = mesh_lib.prefetch_to_mesh(train_src, mesh, depth=cfg.data.device_prefetch)

    # step health guard (train/guard.py): the device half is already wrapped
    # into the compiled step (parallel/dp.py); this is the host accounting
    guard = None
    if cfg.train.guard.enable:
        from ..train.guard import StepGuard

        guard = StepGuard(cfg.train.guard, cfg.train.log_dir if is_coord else None, log)
        if watchdog is not None:
            watchdog.register_info("train_guard", guard.info)

    preempt = _Preemption(log).install()
    preempted = False

    total_epochs = cfg.train.epochs
    spe = trainer.steps_per_epoch
    metric_log = MetricLogger()
    eval_result: dict = {}
    epoch = start_epoch
    best_top1 = float(restored[2].get("best_top1", 0.0)) if restored is not None else 0.0
    host_step = int(ts.step)  # one sync at (re)start, then host-side counting
    trace_active = False
    # integer-step cadences (exact boundaries under fractional epochs/resume)
    eval_cad = StepCadence(cfg.train.eval_every_epochs, spe, host_step)
    ckpt_cad = StepCadence(cfg.train.checkpoint_every_epochs, spe, host_step)
    remat_cad = StepCadence(cfg.prune.remat_epochs, spe, host_step)
    best_ckpt: CheckpointManager | None = None  # created on first new-best eval

    # multi-step dispatch (train.steps_per_dispatch): k steps per jit call,
    # amortizing the per-step host-dispatch/tunnel tax the bench's
    # --dispatch-probe measures. Pruning composes since round 5: the prune
    # event runs in-device after every unrolled sub-step (its own step gate
    # keeps the cadence identical to single dispatches). Only the profiler
    # window still needs step-granular host control (start/stop_trace are
    # host calls at exact step indices) and forces k=1 with a warning —
    # the obs span tracer has no such constraint: its spans time the host
    # side of each dispatch, grouped or not.
    k_dispatch = max(1, cfg.train.steps_per_dispatch)
    if k_dispatch > 1 and cfg.train.profile_start_step:
        log.log("WARNING: steps_per_dispatch>1 is incompatible with the profiler "
                "window; forcing 1")
        k_dispatch = 1

    def build_grouped():
        if k_dispatch < 2:
            return None
        return dp.make_grouped_train_step(trainer.train_step, k_dispatch,
                                          event_fn=trainer.prune_event)

    grouped_step = build_grouped()
    # device-cost accounting fires once per compiled step program: on the
    # first dispatch, and again after a rematerialize rebuild (new shapes =>
    # new executable => new cost)
    cost_recorded = not is_coord

    try:
        while epoch < total_epochs:
            epoch_steps = min(spe, max(int((total_epochs - epoch) * spe), 1))
            t_epoch = time.perf_counter()
            steps_done = 0
            while steps_done < epoch_steps:
                if preempt.requested:
                    preempted = True
                    break
                if grouped_step is not None and epoch_steps - steps_done >= k_dispatch:
                    with tracer.span("data/next", "data", batches=k_dispatch):
                        bs = tuple(next(train_iter) for _ in range(k_dispatch))
                    t_dispatch0 = time.perf_counter()
                    with tracer.span("dispatch/grouped_step", "dispatch", steps=k_dispatch):
                        ts, metric_list = grouped_step(ts, bs, rng)
                    cost_batch = bs[0]
                else:
                    with tracer.span("data/next", "data"):
                        b = next(train_iter)  # already on-mesh (prefetch_to_mesh)
                    t_dispatch0 = time.perf_counter()
                    with tracer.span("dispatch/train_step", "dispatch"):
                        ts, metrics = trainer.train_step(ts, b, rng)
                    metric_list = [metrics]
                    cost_batch = b
                if not cost_recorded:
                    cost_recorded = True
                    _record_step_cost(trainer, ts, cost_batch, rng, reg, tracer, log,
                                      time.perf_counter() - t_dispatch0)
                steps_done += len(metric_list)
                # per-sub-step host processing: metrics entries are lazy
                # device arrays; nothing below syncs unless a cadence fires
                for metrics in metric_list:
                    # host-side counter: int(ts.step) would sync the host
                    # with the device every step and stall async dispatch
                    host_step += 1
                    step_i = host_step
                    metric_log.update(metrics, batch_images=cfg.train.batch_size)
                    if guard is not None:
                        guard.observe(step_i, metrics)  # lazy stash; no sync
                    if watchdog is not None:
                        watchdog.arm(step_i)

                    if cfg.train.profile_start_step and is_coord:
                        if step_i == cfg.train.profile_start_step:
                            # stop is finally-guaranteed (YAMT013): the close
                            # below runs in a finally, and the loop's outer
                            # finally flushes a window still open on ANY exit
                            jax.profiler.start_trace(cfg.train.log_dir + "/trace")
                            trace_active = True
                        elif trace_active and step_i >= cfg.train.profile_start_step + cfg.train.profile_num_steps:
                            try:
                                # true barrier before closing the trace: through
                                # the axon tunnel block_until_ready can return at
                                # dispatch-acknowledge and truncate the trace
                                # window (PROFILE.md "measurement methodology")
                                jax.device_get(metrics["loss"])
                            finally:
                                # a failed barrier sync must still close the
                                # window HERE (the old code left it running
                                # until the outer finally, capturing the whole
                                # unwind into the trace)
                                jax.profiler.stop_trace()
                                trace_active = False
                            log.log(f"profiler trace captured to {cfg.train.log_dir}/trace")

                    if (
                        len(metric_list) == 1
                        and trainer.prune_event is not None
                        and step_i % cfg.prune.mask_interval == 0
                        and step_i <= trainer.prune_stop_step
                    ):
                        # the whole event (reached-target check via in-jit
                        # effective MACs, adaptive-rho feedback — SURVEY.md
                        # §2 #11, conditional mask update) runs on device;
                        # the host gate above only skips the off-cadence
                        # dispatches (the event's own step gate is true
                        # exactly when this condition is). Inside a grouped
                        # dispatch (len(metric_list) == k > 1) the event
                        # already ran in-device after every sub-step — but
                        # an epoch-TAIL step dispatched singly (fewer than k
                        # steps left) has no in-device event and must take
                        # this host path even when grouping is on.
                        with tracer.span("prune/mask_event", "prune", step=step_i):
                            masks, rho_mult = trainer.prune_event(
                                ts.params, ts.masks, ts.rho_mult, ts.step)
                            ts = ts.replace(masks=masks, rho_mult=rho_mult)

                    if step_i % cfg.train.log_every == 0:
                        # the log-boundary host sync: snapshot float()s every
                        # pending metric (blocks on the last dispatched step)
                        with tracer.span("sync/log_metrics", "sync", step=step_i):
                            snap = metric_log.snapshot_and_reset(num_chips=trainer.mesh.size)
                        reg.gauge("train.step").set(step_i)
                        if cfg.prune.enable:
                            snap["effective_macs"] = masking.mask_summary(trainer.net, ts.masks)["effective_macs"]
                            if cfg.prune.rho_schedule == "adaptive":
                                # adaptation lives on device now; one host
                                # sync per log boundary, not per event
                                with tracer.span("sync/rho_mult", "sync"):
                                    snap["rho_mult"] = float(jax.device_get(ts.rho_mult))
                                reg.counter("train.forced_host_syncs").inc()
                        # (decode failures now flow through the registry: the
                        # native loader registers a data.decode_failures pull
                        # gauge that every scalars row snapshots)
                        log.log(format_metrics(f"step {step_i}:", snap))
                        log.scalars(step_i, snap, "train/")
                        if guard is not None:
                            # the guard already rolled back any non-finite
                            # step on device; here it counts the skips and
                            # enforces the budget (train/guard.py) — may
                            # raise TrainHealthError with train_health.json
                            guard.check(step_i)
                        elif snap.get("finite", 1.0) < 1.0:
                            log.error("non-finite loss detected; aborting")
                            raise FloatingPointError("non-finite loss")
                    if cfg.train.check_finite_every and step_i % cfg.train.check_finite_every == 0:
                        # forced host sync — a debug guard, off by default
                        with tracer.span("sync/finite_check", "sync", step=step_i):
                            finite = float(metrics["finite"])
                        reg.counter("train.forced_host_syncs").inc()
                        if finite < 1.0:
                            log.error(f"non-finite loss at step {step_i}")
                            raise FloatingPointError("non-finite loss")
                    if cfg.train.param_checksum_every and step_i % cfg.train.param_checksum_every == 0:
                        with tracer.span("sync/replica_checksum", "sync", step=step_i):
                            div = float(trainer.sync_check(ts.params))
                        reg.counter("train.forced_host_syncs").inc()
                        if div != 0.0:
                            log.error(f"replica divergence {div} at step {step_i}")
                            raise RuntimeError("replica divergence")
            if preempted:
                epoch = host_step / spe  # exact mid-epoch position
                log.log(f"preemption ({preempt.reason}): stopping at step {host_step} "
                        f"(epoch {epoch:.2f})")
                break
            epoch += epoch_steps / spe
            log.log(f"epoch {epoch:.2f} done in {time.perf_counter()-t_epoch:.1f}s")

            # coarse-cadence physical shrink (recompile paid here, not per-step)
            if cfg.prune.enable and remat_cad.due(host_step):
                old_trainer = trainer
                with tracer.span("rebuild/rematerialize", "rebuild", step=host_step):
                    trainer, ts = _maybe_rematerialize(trainer, ts, log)
                if trainer is not old_trainer:
                    # shapes (and the prune event's cost table) changed —
                    # the grouped program must be rebuilt against the new
                    # trainer; identity check avoids a gratuitous retrace
                    # when nothing died
                    reg.counter("train.rebuilds").inc()
                    with tracer.span("rebuild/grouped_step", "rebuild"):
                        grouped_step = build_grouped()
                    cost_recorded = not is_coord  # new executable: re-account its cost
                if watchdog is not None:
                    watchdog.arm(host_step, phase="rematerialize")

            # final eval AND final checkpoint always run, symmetrically, even
            # with the periodic knobs set to 0
            final = epoch >= total_epochs
            if eval_cad.due(host_step) or final:
                eval_result = evaluate(trainer, ts, cfg, watchdog=watchdog)
                if eval_result["top1"] > best_top1:  # reference: best-acc tracking
                    best_top1 = eval_result["top1"]
                    if cfg.train.keep_best:
                        # single-slot best checkpoint (reference: best.pth) —
                        # separate dir so resume always uses the latest while
                        # the best stays evaluable via train.pretrained
                        if best_ckpt is None:
                            best_ckpt = CheckpointManager(
                                cfg.train.log_dir + "/ckpt_best", max_to_keep=1, barrier_prefix="best"
                            )
                            best_box.append(best_ckpt)  # shutdown wait/close (_run_impl)
                        best_ckpt.save(
                            int(ts.step), trainer.net, jax.device_get(trainer.checkpoint_view(ts)),
                            extra={"epoch": epoch, "best_top1": best_top1},
                        )
                eval_result["best_top1"] = best_top1
                log.log(format_metrics(f"eval @ epoch {epoch:.2f}:", eval_result))
                log.scalars(int(ts.step), eval_result, "eval/")
                if watchdog is not None:
                    watchdog.arm(host_step, phase="eval")

            if ckpt_cad.due(host_step) or final:
                # orbax coordinates multi-host saves internally; every process
                # calls in. device_get: the async save must not read buffers
                # the next step will donate. checkpoint_view makes the tree
                # fully replicated first, so the host copy is multi-host-safe.
                ckpt.save(
                    int(ts.step), trainer.net, jax.device_get(trainer.checkpoint_view(ts)),
                    extra={"epoch": epoch, "best_top1": best_top1},
                )
                if watchdog is not None:
                    watchdog.arm(host_step, phase="checkpoint")

    finally:
        preempt.uninstall()
        if trace_active:
            # training ended (or raised) inside the capture window: flush
            # the trace rather than losing it — and never let a failing
            # stop mask the exception that got us here
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — best-effort flush on unwind
                log.log(f"profiler stop on exit failed ({type(e).__name__}: {e})")

    if guard is not None:
        guard.check(host_step)  # flush verdicts the last log window missed

    if preempted:
        # final SYNCHRONOUS checkpoint: save, then WAIT — the process exits
        # right after, so an async enqueue alone could be reaped half-written
        # (exactly the torn state the digest sidecar would then reject)
        log.log(f"preemption checkpoint: saving step {host_step} synchronously")
        ckpt.save(
            host_step, trainer.net, jax.device_get(trainer.checkpoint_view(ts)),
            extra={"epoch": epoch, "best_top1": best_top1, "preempted": True},
        )
        ckpt.wait()
        reg.counter("train.preemptions").inc()
        if is_coord:
            marker = {
                "step": host_step,
                "epoch": epoch,
                "reason": preempt.reason,
                "checkpoint_dir": cfg.train.log_dir + "/ckpt",
            }
            marker_path = os.path.join(cfg.train.log_dir, PREEMPT_MARKER_NAME)
            tmp = f"{marker_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(marker, f, indent=1)
            os.replace(tmp, marker_path)
            log.log(f"resume marker -> {marker_path}; restart with train.resume=true "
                    "to continue from here")
        final = {"epoch": epoch, "step": host_step, "preempted": True,
                 **{f"eval_{k}": v for k, v in eval_result.items()}}
        log.log(format_metrics("preempted:", final))
        return final

    if cfg.prune.enable:
        # apply any remaining masks physically and emit the searched result
        # as a standalone spec (reference: 'final architecture == surviving
        # channels; emit as block-spec', SURVEY.md §3.2)
        with tracer.span("rebuild/rematerialize", "rebuild", step=host_step):
            trainer, ts = _maybe_rematerialize(trainer, ts, log)
        from ..models.serialize import network_to_dict

        prof_final = profile_network(trainer.net)
        if is_coord:
            payload = {
                "network": network_to_dict(trainer.net),
                "macs": int(prof_final.total_macs),
                "params": int(prof_final.total_params),
                "step": int(ts.step),
            }
            path = os.path.join(cfg.train.log_dir, "searched_arch.json")
            with open(path, "w") as f:
                json.dump(payload, f, indent=1)
            log.log(
                f"searched architecture -> {path} "
                f"({prof_final.total_macs/1e6:.1f}M MACs, {prof_final.total_params/1e6:.2f}M params)"
            )

    # manager wait+close happens in _run_impl's finally — on THIS path and on
    # every error path, wait always precedes close (an in-flight async save
    # is never abandoned half-written)
    final = {"epoch": epoch, **{f"eval_{k}": v for k, v in eval_result.items()}}
    log.log(format_metrics("done:", final))
    return final


def main(argv=None):
    cfg = parse_cli(sys.argv[1:] if argv is None else argv)
    run(cfg)


if __name__ == "__main__":
    main()
