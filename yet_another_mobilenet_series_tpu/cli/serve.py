"""Serving entry point — ``python -m yet_another_mobilenet_series_tpu.cli.serve
app:<yaml> [key=value ...]`` (sibling of cli.train / cli.profile).

Two phases, both optional, driven by the ``serve:`` config block:

1. **export** (``serve.export_from`` set): checkpoint -> InferenceBundle at
   ``serve.bundle`` — prune masks hard-applied, EMA weights selected, BN
   folded into conv weights (serve/export.py).
2. **serve** (``serve.requests`` > 0): load the bundle, AOT-warm the
   engine's (bucket, image_size) ladder, and drive a synthetic closed-loop
   load of ``serve.requests`` single-image requests from ``serve.clients``
   client threads through the batcher — the pipelined continuous-batching
   one by default (``serve.pipelined``, serve/pipeline.py), or the legacy
   sync micro-batcher — the in-process stand-in for an RPC front door,
   exercising the exact queue/coalesce/dispatch path one would sit behind
   one. Prints p50/p99 end-to-end latency and QPS; with a log_dir, metrics
   + obs_registry.json land where scripts/obs_report.py reads them.

``serve.requests=0`` with a bundle still warms up every bucket — a
deploy-time smoke that the artifact compiles and serves shape-correctly.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from ..config import Config, parse_cli
from ..obs import registry as obs_registry
from ..obs import trace as obs_trace
from ..parallel import mesh as mesh_lib
from ..serve.batcher import MicroBatcher, QueueFull
from ..serve.engine import InferenceEngine
from ..serve.pipeline import PipelinedBatcher
from ..serve.export import export_checkpoint, load_bundle
from ..utils.logging import Logger


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(round(q * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _drive_load(cfg: Config, batcher: MicroBatcher, image_size: int, log: Logger) -> dict:
    """Closed-loop synthetic clients: each thread submits one request, waits
    for its logits, repeats. Returns the latency/QPS summary."""
    import threading

    n_total = cfg.serve.requests
    n_clients = max(1, cfg.serve.clients)
    rng = np.random.RandomState(0)
    image = rng.normal(0, 1, (image_size, image_size, 3)).astype(np.float32)
    latencies: list[float] = []
    errors = {"shed": 0, "rejected": 0}
    lock = threading.Lock()
    counter = {"left": n_total}

    def client():
        while True:
            with lock:
                if counter["left"] <= 0:
                    return
                counter["left"] -= 1
            t0 = time.perf_counter()
            try:
                fut = batcher.submit(image, deadline_ms=cfg.serve.deadline_ms or None)
                fut.result(timeout=60)
            except QueueFull:
                with lock:
                    errors["rejected"] += 1
                time.sleep(0.001)  # back off, as a real client would
                continue
            except Exception:  # noqa: BLE001 — shed/engine failure: count, keep driving
                with lock:
                    errors["shed"] += 1
                continue
            with lock:
                latencies.append(time.perf_counter() - t0)

    threads = [threading.Thread(target=client, daemon=True) for _ in range(n_clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    latencies.sort()
    summary = {
        "requests": n_total,
        "completed": len(latencies),
        "shed": errors["shed"],
        "rejected_full": errors["rejected"],
        "wall_s": wall,
        "qps": len(latencies) / wall if wall > 0 else 0.0,
        "p50_ms": _percentile(latencies, 0.50) * 1e3,
        "p99_ms": _percentile(latencies, 0.99) * 1e3,
    }
    log.log(
        f"load: {summary['completed']}/{n_total} ok ({summary['shed']} shed, "
        f"{summary['rejected_full']} rejected), {summary['qps']:.1f} qps, "
        f"p50 {summary['p50_ms']:.2f} ms, p99 {summary['p99_ms']:.2f} ms"
    )
    return summary


def run(cfg: Config) -> dict:
    is_coord = mesh_lib.is_coordinator()
    log = Logger(cfg.train.log_dir, enabled=is_coord, tensorboard=False)
    reg = obs_registry.get_registry()
    log.set_registry(reg)
    tracer = obs_trace.configure(enabled=bool(cfg.obs.trace) and is_coord, ring_size=cfg.obs.trace_ring_size)
    result: dict = {}
    try:
        bundle_dir = cfg.serve.bundle
        if cfg.serve.export_from:
            if not bundle_dir:
                bundle_dir = os.path.join(cfg.train.log_dir, "bundle")
            export_checkpoint(cfg.serve.export_from, bundle_dir, use_ema=cfg.serve.use_ema)
            log.log(f"exported {cfg.serve.export_from} -> {bundle_dir}")
            result["bundle"] = bundle_dir
        if not bundle_dir:
            raise ValueError("serve: needs serve.bundle and/or serve.export_from")

        bundle = load_bundle(bundle_dir)
        mesh = mesh_lib.make_mesh(cfg.dist.num_devices) if cfg.serve.data_parallel else None
        engine = InferenceEngine(
            bundle,
            buckets=cfg.serve.buckets,
            compute_dtype=cfg.serve.compute_dtype,
            mesh=mesh,
            donate_input=cfg.serve.donate_input,
            image_size=cfg.data.image_size,
            image_sizes=cfg.serve.image_sizes,
        )
        if cfg.serve.warmup:
            t0 = time.perf_counter()
            engine.warmup()
            log.log(
                f"warmup: compiled buckets {engine.buckets} x sizes {engine.image_sizes} "
                f"in {time.perf_counter() - t0:.1f}s"
            )
        if cfg.serve.requests > 0:
            common = dict(
                max_batch=cfg.serve.max_batch,
                max_wait_ms=cfg.serve.max_wait_ms,
                queue_depth=cfg.serve.queue_depth,
                default_deadline_ms=cfg.serve.deadline_ms,
            )
            if cfg.serve.pipelined:
                batcher = PipelinedBatcher(engine, max_inflight=cfg.serve.max_inflight, **common)
            else:
                batcher = MicroBatcher(engine.predict, **common)
            batcher.start()
            try:
                result.update(_drive_load(cfg, batcher, cfg.data.image_size, log))
            finally:
                batcher.stop()
        return result
    finally:
        if tracer.enabled and cfg.train.log_dir and is_coord:
            path = tracer.write(os.path.join(cfg.train.log_dir, "obs_trace.json"))
            log.log(f"span trace -> {path}")
        if is_coord and cfg.train.log_dir:
            os.makedirs(cfg.train.log_dir, exist_ok=True)
            with open(os.path.join(cfg.train.log_dir, "obs_registry.json"), "w") as f:
                json.dump(reg.snapshot(), f, indent=1, sort_keys=True)
        log.close()


def main(argv=None):
    cfg = parse_cli(sys.argv[1:] if argv is None else argv)
    return run(cfg)


if __name__ == "__main__":
    main()
