"""Serving entry point — ``python -m yet_another_mobilenet_series_tpu.cli.serve
app:<yaml> [key=value ...]`` (sibling of cli.train / cli.profile).

Three phases, all optional, driven by the ``serve:`` config block:

1. **export** (``serve.export_from`` set): checkpoint -> InferenceBundle at
   ``serve.bundle`` — prune masks hard-applied, EMA weights selected, BN
   folded into conv weights (serve/export.py). With
   ``serve.quant.weights=int8`` the export additionally runs the gated
   post-training quantization pass (seeded synthetic calibration batch
   normalized with ``data.mean/std``; refused below the top-1 gate).
2. **synthetic load** (``serve.requests`` > 0): load the bundle, AOT-warm
   the engine's (bucket, image_size) ladder, and drive a synthetic
   closed-loop load of ``serve.requests`` single-image requests from
   ``serve.clients`` client threads through the batcher — the pipelined
   continuous-batching one by default (``serve.pipelined``,
   serve/pipeline.py), or the legacy sync micro-batcher. Prints p50/p99
   end-to-end latency and QPS; with a log_dir, metrics + obs_registry.json
   land where scripts/obs_report.py reads them.
3. **listen** (``serve.listen.enable`` or the ``--listen`` shorthand): the
   fault-tolerant front door — a loopback HTTP server (serve/frontend.py)
   in front of priority/QoS admission control, bounded retry, and a
   circuit breaker (serve/admission.py). ``POST /predict`` takes
   ``X-Priority`` / ``X-Deadline-Ms`` headers; ``GET /healthz`` reports
   breaker + queue state. SIGTERM/SIGINT stops accepting and drains
   in-flight work bounded by ``serve.drain_timeout_s``; the bound address
   lands in ``<log_dir>/listen_addr.json`` so callers never race the bind.
   ``serve.faults.enable`` wraps the engine in the seeded chaos injector
   (serve/faults.py) for recovery drills. With
   ``obs.watchdog_deadline_s`` > 0 a stall watchdog guards the serving
   loop, its hang report carrying batcher threads + window + breaker state.

``serve.requests=0`` with a bundle still warms up every bucket — a
deploy-time smoke that the artifact compiles and serves shape-correctly.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

import numpy as np

from ..config import Config, parse_cli
from ..obs import device as obs_device
from ..obs import registry as obs_registry
from ..obs import trace as obs_trace
from ..obs.watchdog import StallWatchdog
from ..parallel import mesh as mesh_lib
from ..serve.admission import AdmissionController
from ..serve.batcher import MicroBatcher, QueueFull
from ..serve.brownout import BrownoutController
from ..serve.engine import InferenceEngine
from ..serve.signals import SignalReader
from ..serve.faults import FaultyEngine
from ..serve.frontend import Frontend, write_listen_addr
from ..serve.pipeline import PipelinedBatcher
from ..serve import quant
from ..serve.export import export_checkpoint, load_bundle
from ..utils.logging import Logger


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(round(q * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _synthetic_image(rng, image_size: int, wire: str) -> np.ndarray:
    """One synthetic client image in the configured wire's input space:
    normalized f32 pixels on the float32 wire (pipeline semantics), raw u8
    pixels on the uint8 wire (the engine denormalizes on device)."""
    if wire == "uint8":
        return rng.randint(0, 256, (image_size, image_size, 3)).astype(np.uint8)
    return rng.normal(0, 1, (image_size, image_size, 3)).astype(np.float32)


def _drive_load(cfg: Config, batcher: MicroBatcher, image_size: int, log: Logger) -> dict:
    """Closed-loop synthetic clients: each thread submits one request, waits
    for its logits, repeats. Returns the latency/QPS summary."""
    n_total = cfg.serve.requests
    n_clients = max(1, cfg.serve.clients)
    rng = np.random.RandomState(0)
    image = _synthetic_image(rng, image_size, cfg.serve.quant.wire)
    latencies: list[float] = []
    errors = {"shed": 0, "rejected": 0, "crashed": 0}
    lock = threading.Lock()
    counter = {"left": n_total}

    def client_inner():
        while True:
            with lock:
                if counter["left"] <= 0:
                    return
                counter["left"] -= 1
            t0 = time.perf_counter()
            try:
                fut = batcher.submit(image, deadline_ms=cfg.serve.deadline_ms or None)
                fut.result(timeout=60)
            except QueueFull:
                with lock:
                    errors["rejected"] += 1
                time.sleep(0.001)  # back off, as a real client would
                continue
            except Exception:  # noqa: BLE001 — shed/engine failure: count, keep driving
                with lock:
                    errors["shed"] += 1
                continue
            with lock:
                latencies.append(time.perf_counter() - t0)

    def client():
        # YAMT011: a silently-dead client thread would skew the measured load
        try:
            client_inner()
        except Exception:  # noqa: BLE001 — count the loss, keep the run honest
            with lock:
                errors["crashed"] += 1

    threads = [threading.Thread(target=client, daemon=True) for _ in range(n_clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    latencies.sort()
    summary = {
        "requests": n_total,
        "completed": len(latencies),
        "shed": errors["shed"],
        "rejected_full": errors["rejected"],
        "client_crashes": errors["crashed"],
        "wall_s": wall,
        "qps": len(latencies) / wall if wall > 0 else 0.0,
        "p50_ms": _percentile(latencies, 0.50) * 1e3,
        "p99_ms": _percentile(latencies, 0.99) * 1e3,
    }
    log.log(
        f"load: {summary['completed']}/{n_total} ok ({summary['shed']} shed, "
        f"{summary['rejected_full']} rejected), {summary['qps']:.1f} qps, "
        f"p50 {summary['p50_ms']:.2f} ms, p99 {summary['p99_ms']:.2f} ms"
    )
    return summary


def _make_batcher(cfg: Config, engine) -> MicroBatcher:
    common = dict(
        max_batch=cfg.serve.max_batch,
        max_wait_ms=cfg.serve.max_wait_ms,
        queue_depth=cfg.serve.queue_depth,
        default_deadline_ms=cfg.serve.deadline_ms,
        drain_timeout_s=cfg.serve.drain_timeout_s,
        # submit-side coercion follows the engine's wire (serve.quant.wire);
        # FaultyEngine proxies the attribute, bare doubles default to f32
        wire_dtype=getattr(engine, "wire_np_dtype", np.float32),
    )
    if cfg.serve.pipelined:
        return PipelinedBatcher(
            engine,
            max_inflight=cfg.serve.max_inflight,
            # back-to-back dispatch rides the overlap block: a saturated
            # bucket dispatches runs with one completion wake-up per run
            run_max=cfg.serve.overlap.run_max if cfg.serve.overlap.enable else 1,
            # ring feed/drain engages iff the ENGINE has ring_slots > 0
            # (serve.ring.enable wired into eng_kw); min_fill only sets the
            # engagement threshold here
            ring_min_fill=cfg.serve.ring.min_fill,
            **common,
        )
    return MicroBatcher(engine.predict, **common)


def _serving_info(batcher, admission) -> dict:
    """The watchdog hang-report 'serving' section: worker thread liveness,
    in-flight window occupancy, breaker + per-class queue state, and the
    OLDEST in-flight request's id/class/age/phase — a wedged window names
    whose request is stuck and which hop it is stuck at."""
    info: dict = {"admission": admission.state(),
                  "oldest_request": admission.oldest_inflight()}
    if hasattr(batcher, "worker_threads"):
        info["batcher_threads"] = batcher.worker_threads()
        info["inflight"] = batcher.inflight()
    else:
        t = batcher._thread
        info["batcher_threads"] = [] if t is None else [{"name": t.name, "alive": t.is_alive()}]
    return info


def _listen(cfg: Config, engine, log: Logger, reg, tracer, zoo=None) -> dict:
    """The front-door serving loop: HTTP frontend + admission + batcher,
    running until SIGTERM/SIGINT."""
    stop_event = threading.Event()

    def _on_signal(signum, frame):
        log.log(f"signal {signum}: stopping accept loop, draining in-flight work")
        stop_event.set()

    # only the main thread may install handlers; an embedded (test) run
    # drives shutdown through the returned stop_event instead
    try:
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
    except ValueError:
        pass

    # fleet-spawned replicas (cli/fleet.py sets YAMT_FLEET_PARENT) self-
    # drain when their supervisor PROCESS disappears — a supervisor killed
    # -9 cannot run its drain paths, and an orphaned replica would hold its
    # port and device lease forever. getppid() changing away from the
    # recorded pid (reparenting to init/subreaper) is the death signal.
    supervisor_pid = os.environ.get("YAMT_FLEET_PARENT")

    def _orphan_watch():
        try:  # YAMT011: a dead watcher silently disables orphan protection
            parent = int(supervisor_pid)
            while not stop_event.wait(0.5):
                if os.getppid() != parent:
                    log.log(f"supervisor {parent} gone (now child of {os.getppid()}): "
                            "orphaned — draining")
                    reg.counter("serve.orphan_exits").inc()
                    stop_event.set()
                    return
        except Exception as e:  # noqa: BLE001 — contain, count, report
            reg.counter("serve.thread_crashes").inc()
            log.log(f"[serve] orphan watcher crashed: {type(e).__name__}: {e}")

    if supervisor_pid:
        threading.Thread(target=_orphan_watch, name="serve-orphan-watch", daemon=True).start()

    batcher = _make_batcher(cfg, engine).start()
    watchdog = None
    if cfg.obs.watchdog_deadline_s > 0 and cfg.train.log_dir:
        watchdog = StallWatchdog(
            cfg.train.log_dir,
            cfg.obs.watchdog_deadline_s,
            tracer=tracer,
            registry=reg,
            poll_s=cfg.obs.watchdog_poll_s,
            logger=log,
        )
    admission = AdmissionController.from_config(
        batcher,
        cfg.serve.admission,
        heartbeat=(lambda: watchdog.arm(phase="serve")) if watchdog is not None else None,
        # zoo'd replicas validate X-Model at the door and meter per-model
        # quotas (serve/zoo.py admission_kwargs); a bundle replica keeps the
        # pre-zoo behavior (no model vocabulary, nothing to reject)
        **(zoo.admission_kwargs() if zoo is not None else {}),
    )
    if watchdog is not None:
        watchdog.register_info("serving", lambda: _serving_info(batcher, admission))
        watchdog.start()
    # brownout ladder at the REPLICA tier: the controller reads this
    # process's own admission-side signals (windowed per-class p99 +
    # admitted backlog + breaker) and actuates the batcher (fill-or-flush)
    # and the admission controller (class shed / margin / retries)
    brownout = None
    if cfg.serve.brownout.enable:
        brownout = BrownoutController.from_config(
            cfg.serve.brownout,
            SignalReader(
                latency_family="serve.latency_seconds",
                signal_class=cfg.serve.brownout.signal_class,
                queue_depth_fn=admission.queued_total,
            ),
            targets=(batcher, admission),
        ).start()
        log.log(f"brownout ladder armed (L0..L{cfg.serve.brownout.max_level}, "
                f"up p99 > {cfg.serve.brownout.up_p99_ms:.0f}ms or "
                f"queue > {cfg.serve.brownout.up_queue_depth:.0f})")
    # HTTP-triggered jax.profiler capture (obs/device.py): xplane dumps land
    # in <log_dir>/trace (or serve.listen.profile_dir) for trace_ops.py; the
    # drain path below guarantees a still-open window closes at shutdown
    profile_dir = cfg.serve.listen.profile_dir or (
        os.path.join(cfg.train.log_dir, "trace") if cfg.train.log_dir else ""
    )
    profiler = obs_device.ProfilerCapture(profile_dir) if profile_dir else None
    frontend = Frontend(
        admission,
        host=cfg.serve.listen.host,
        port=cfg.serve.listen.port,
        request_timeout_s=cfg.serve.listen.request_timeout_s,
        retry_after_s=cfg.serve.admission.breaker_cooldown_s,
        profiler=profiler,
        replica_id=cfg.serve.listen.replica_id,
    ).start()
    # ephemeral ports (listen.port=0) make N replicas on one host trivial;
    # the bound port is published ATOMICALLY (temp + rename) so a polling
    # supervisor (cli/fleet.py) never reads a partial JSON
    addr = {"host": cfg.serve.listen.host, "port": frontend.port, "pid": os.getpid(),
            "replica_id": frontend.replica_id}
    if cfg.train.log_dir:
        write_listen_addr(cfg.train.log_dir, addr)
    log.log(f"listening on {frontend.url} (POST /predict, GET /healthz|/metrics|/varz)")
    # TTL-lease self-registration (serve.listen.register_to): the replica
    # heartbeats its OWN address into a fleet router that never spawned it
    # — the multi-host membership path. The lease outliving the heartbeat
    # is the router's signal this process (or the route to it) vanished.
    reg_client = None
    if cfg.serve.listen.register_to:
        from ..serve.client import ClientHTTPError, ReplicaClient
        r_host, r_port = cfg.serve.listen.register_to.rsplit(":", 1)
        ttl_s = cfg.serve.listen.register_ttl_s
        reg_client = ReplicaClient(r_host, int(r_port), timeout_s=5.0,
                                   connect_timeout_s=2.0)
        # the lease's served-model advertisement ({name: digest}): the
        # router routes a model only to replicas advertising it, and refuses
        # a digest that conflicts with another live replica's for the name
        lease_models = zoo.lease_models() if zoo is not None else None

        def _heartbeat():
            try:  # YAMT011: a dead heartbeat thread = silent lease expiry
                period = max(ttl_s / 3.0, 0.1)
                while not stop_event.is_set():
                    try:
                        reg_client.register(addr["host"], addr["port"], ttl_s=ttl_s,
                                            replica_id=frontend.replica_id,
                                            models=lease_models)
                        reg.counter("serve.register_heartbeats").inc()
                    except ClientHTTPError as e:
                        if e.tag == "digest_conflict":
                            # the fleet serves a DIFFERENT artifact under one
                            # of our model names: renewing can never succeed,
                            # so stop beating loudly instead of spinning
                            reg.counter("serve.register_conflicts").inc()
                            log.log(f"[serve] register REFUSED (digest conflict): {e}")
                            return
                        reg.counter("serve.register_failures").inc()
                    except Exception:  # noqa: BLE001 — the router may be down;
                        # keep beating: the next renewal re-admits us
                        reg.counter("serve.register_failures").inc()
                    stop_event.wait(period)
            except Exception as e:  # noqa: BLE001 — contain, count, report
                reg.counter("serve.thread_crashes").inc()
                log.log(f"[serve] register heartbeat crashed: {type(e).__name__}: {e}")

        threading.Thread(target=_heartbeat, name="serve-register", daemon=True).start()
        log.log(f"registering with {cfg.serve.listen.register_to} "
                f"(ttl={ttl_s:.1f}s, heartbeat every {max(ttl_s / 3.0, 0.1):.1f}s)")
    try:
        stop_event.wait()
    finally:
        t0 = time.perf_counter()
        if reg_client is not None:
            try:
                # clean drain: leave the fleet NOW instead of via TTL lapse
                reg_client.deregister(addr["host"], addr["port"])
            except Exception:  # noqa: BLE001 — the router may already be gone;
                # the lease lapses on its own, so count it and move on
                reg.counter("serve.deregister_failures").inc()
            reg_client.close()
        frontend.stop()
        if brownout is not None:
            brownout.stop()
        if profiler is not None:
            # a capture the operator never stopped must not outlive the
            # server (the drain-path half of the YAMT013 discipline)
            profiler.stop_if_active()
        batcher.stop(drain=True)  # bounded by serve.drain_timeout_s
        if watchdog is not None:
            watchdog.stop()
        drain_s = time.perf_counter() - t0
        timeouts = int(reg.snapshot().get("serve.drain_timeouts", 0))
        log.log(f"drained in {drain_s:.2f}s ({'clean' if not timeouts else 'DRAIN TIMEOUT'})")
    return {"listened": True, **addr, "drain_s": drain_s, "drain_timeouts": timeouts}


def run(cfg: Config) -> dict:
    is_coord = mesh_lib.is_coordinator()
    log = Logger(cfg.train.log_dir, enabled=is_coord, tensorboard=False)
    reg = obs_registry.get_registry()
    if cfg.obs.histogram_buckets:
        # before any serving histogram exists: the ladder applies at creation
        reg.set_default_buckets(cfg.obs.histogram_buckets)
    # version attribution (/metrics build_info family) + device memory gauges
    reg.set_build_info(obs_device.build_info())
    obs_device.install_memory_gauges(reg)
    log.set_registry(reg)
    tracer = obs_trace.configure(
        enabled=bool(cfg.obs.trace) and is_coord, ring_size=cfg.obs.trace_ring_size,
        # the merged fleet trace's process-lane label (trace_merge.py):
        # replicas identify by their supervisor-assigned replica_id
        process_name=cfg.serve.listen.replica_id or f"replica pid-{os.getpid()}",
    )
    result: dict = {}
    try:
        bundle_dir = cfg.serve.bundle
        if cfg.serve.export_from:
            if not bundle_dir:
                bundle_dir = os.path.join(cfg.train.log_dir, "bundle")
            calib = None
            if cfg.serve.quant.weights == "int8":
                # held-out calibration batch for the int8 gate: seeded
                # synthetic u8 pixels normalized with the pipeline's
                # mean/std (no dataset is wired into the serve CLI; the
                # bundle's provenance records the synthetic source)
                q = cfg.serve.quant
                crng = np.random.RandomState(q.calib_seed)
                raw = crng.randint(
                    0, 256,
                    (q.calib_batches * q.calib_batch_size,
                     cfg.data.image_size, cfg.data.image_size, 3),
                ).astype(np.uint8)
                calib = quant.normalize_reference(raw, cfg.data.mean, cfg.data.std)
            export_checkpoint(
                cfg.serve.export_from, bundle_dir, use_ema=cfg.serve.use_ema,
                quant_weights=cfg.serve.quant.weights, calib_images=calib,
                int8_top1_min=cfg.serve.quant.int8_top1_min,
            )
            log.log(f"exported {cfg.serve.export_from} -> {bundle_dir}"
                    + (" (int8 weights, parity-gated)" if calib is not None else ""))
            result["bundle"] = bundle_dir
        # multi-model zoo (serve.zoo.models set): N named bundles behind one
        # engine/admission edge, each request picking its tenant via X-Model
        zoo = None
        if cfg.serve.zoo.models:
            from ..serve.zoo import ModelZoo
            zoo = ModelZoo.from_config(cfg.serve.zoo)
            log.log(f"zoo: serving {', '.join(zoo.models)} (default {zoo.default})")
        if not bundle_dir and zoo is None:
            raise ValueError(
                "serve: needs serve.bundle, serve.zoo.models, and/or serve.export_from")

        mesh = mesh_lib.make_mesh(cfg.dist.num_devices) if cfg.serve.data_parallel else None
        eng_kw = dict(
            buckets=cfg.serve.buckets,
            compute_dtype=cfg.serve.compute_dtype,
            mesh=mesh,
            donate_input=cfg.serve.donate_input,
            image_size=cfg.data.image_size,
            image_sizes=cfg.serve.image_sizes,
            fuse_ladder=cfg.serve.fuse_chunks.ladder if cfg.serve.fuse_chunks.enable else (),
            offladder_cache=cfg.serve.offladder_cache,
            overlap_staging=cfg.serve.overlap.enable,
            staging_slots=cfg.serve.overlap.staging_slots,
            wire=cfg.serve.quant.wire,
            wire_mean=cfg.data.mean,
            wire_std=cfg.data.std,
            # device-resident request ring (serve/ring.py): one masked-scan
            # dispatch per steady-state window. Gated off under the mesh
            # here (the engine would refuse the combination) — the same
            # per-chunk fallback rule fusion follows under data_parallel
            ring_slots=cfg.serve.ring.slots
            if (cfg.serve.ring.enable and mesh is None) else 0,
        )
        if zoo is not None:
            engine = InferenceEngine(**zoo.engine_kwargs(), **eng_kw)
        else:
            bundle = load_bundle(bundle_dir)
            engine = InferenceEngine(bundle, **eng_kw)
        # quantization mode rides the build_info family (/metrics, /varz):
        # a scraped fleet can group replicas by the bytes they serve with
        reg.set_build_info({**obs_device.build_info(), "quant_mode": engine.quant_mode})
        if cfg.serve.warmup:
            t0 = time.perf_counter()
            engine.warmup()
            log.log(
                f"warmup: compiled buckets {engine.buckets} x sizes {engine.image_sizes}"
                + (f" + fused K {engine.fuse_ladder}" if engine.fuse_ladder else "")
                + f" in {time.perf_counter() - t0:.1f}s"
            )
        engine = FaultyEngine.from_config(engine, cfg.serve.faults)
        if cfg.serve.faults.enable:
            log.log(
                f"CHAOS: fault injection on (seed={cfg.serve.faults.seed}, "
                f"failure_rate={cfg.serve.faults.failure_rate}, "
                f"fail_first_n={cfg.serve.faults.fail_first_n})"
            )
        if cfg.serve.requests > 0:
            batcher = _make_batcher(cfg, engine)
            batcher.start()
            try:
                result.update(_drive_load(cfg, batcher, cfg.data.image_size, log))
            finally:
                batcher.stop()
        if cfg.serve.listen.enable:
            result.update(_listen(cfg, engine, log, reg, tracer, zoo=zoo))
        return result
    finally:
        if tracer.enabled and cfg.train.log_dir and is_coord:
            path = tracer.write(os.path.join(cfg.train.log_dir, "obs_trace.json"))
            log.log(f"span trace -> {path}")
        if is_coord and cfg.train.log_dir:
            os.makedirs(cfg.train.log_dir, exist_ok=True)
            with open(os.path.join(cfg.train.log_dir, "obs_registry.json"), "w") as f:
                json.dump(reg.snapshot(), f, indent=1, sort_keys=True)
        log.close()


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # `--listen` is sugar for serve.listen.enable=true (the front-door mode
    # named by ROADMAP item 1); everything else stays app:/key=value
    argv = ["serve.listen.enable=true" if a == "--listen" else a for a in argv]
    cfg = parse_cli(argv)
    return run(cfg)


if __name__ == "__main__":
    main()
