"""Cross-replica sharding of the weight update (PAPERS.md:5,
arXiv:2004.13336) — the ZeRO-style option on top of data parallelism.

Instead of every replica redundantly applying the identical optimizer update
(replicated RMSProp/momentum accumulators, 2x param memory each), the update
is split across the 'data' axis:

  grads --psum_scatter--> 1/N shard per device          (half the allreduce)
  each device updates its shard (accumulators live sharded: memory/N)
  new params --all_gather--> replicated again           (the other half)

Total communication matches plain DP's allreduce (reduce-scatter+all-gather
== allreduce), but update FLOPs and optimizer memory drop by N. For the
MobileNet-scale models here the win is small; the component exists because
it is the one beyond-DP parallelism with grounding in the reference workload
(SURVEY.md §2 parallelism inventory) and it matters at the 256-chip
acceptance point's batch sizes.

Used inside the shard_map'd train step: ``make_zero_update`` returns the
per-device update; ``init_opt_state``/``opt_state_specs`` build the globally
sharded accumulator tree ((n*chunk,) flat leaves, PartitionSpec('data')).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.compat import shard_map
from .mesh import DATA_AXIS


def _chunk(total: int, n: int) -> int:
    return -(-total // n)


def _pad_flat(x, n: int):
    """(total,) -> (n*chunk,) zero-padded flat view."""
    total = x.size
    chunk = _chunk(total, n)
    flat = x.reshape(-1)
    return jnp.pad(flat, (0, n * chunk - total))


def _shard_of(x, idx, n: int):
    """This device's (chunk,) slice of a (replicated) leaf."""
    chunk = _chunk(x.size, n)
    return lax.dynamic_slice(_pad_flat(x, n), (idx * chunk,), (chunk,))


def shard_params_local(params, idx, n: int):
    return jax.tree.map(lambda p: _shard_of(p, idx, n), params)


def make_zero_update(optimizer: optax.GradientTransformation, n: int, axis_name: str = DATA_AXIS):
    """Returns update(grads_local, opt_state_shard, params) ->
    (new_params_replicated, new_opt_state_shard, global_grad_norm).
    Call inside shard_map; ``grads_local`` are this device's UN-averaged
    local gradients (no pmean — the mean happens in the psum_scatter)."""

    def update(grads, opt_state_sh, params):
        idx = lax.axis_index(axis_name)

        def scatter(g):
            chunk = _chunk(g.size, n)
            g2 = _pad_flat(g, n).reshape(n, chunk)
            return lax.psum_scatter(g2, axis_name, scatter_dimension=0, tiled=False) / n

        g_sh = jax.tree.map(scatter, grads)
        p_sh = shard_params_local(params, idx, n)
        updates, new_opt_sh = optimizer.update(g_sh, opt_state_sh, p_sh)
        new_p_sh = optax.apply_updates(p_sh, updates)

        def gather(ns, orig):
            full = lax.all_gather(ns, axis_name, tiled=True)  # (n*chunk,)
            return full[: orig.size].reshape(orig.shape).astype(orig.dtype)

        new_params = jax.tree.map(gather, new_p_sh, params)
        gnorm = jnp.sqrt(lax.psum(optax.global_norm(g_sh) ** 2, axis_name))
        return new_params, new_opt_sh, gnorm

    return update


def _local_init(optimizer, params, idx, n):
    return optimizer.init(shard_params_local(params, idx, n))


def opt_state_specs(optimizer: optax.GradientTransformation, params, n: int):
    """PartitionSpec tree for the globally-sharded optimizer state: flat
    accumulator leaves are P('data'); scalar bookkeeping (e.g. schedule
    counts) is replicated."""
    abstract = jax.eval_shape(lambda p: _local_init(optimizer, p, 0, n), params)
    return jax.tree.map(lambda l: P(DATA_AXIS) if l.ndim >= 1 else P(), abstract)


def init_opt_state(optimizer: optax.GradientTransformation, params, mesh: Mesh):
    """Builds the sharded optimizer state as global arrays over the mesh:
    each accumulator leaf is (n*chunk,) flat, device d holding shard d."""
    n = mesh.size
    specs = opt_state_specs(optimizer, params, n)
    fn = shard_map(
        lambda p: _local_init(optimizer, p, lax.axis_index(DATA_AXIS), n),
        mesh=mesh,
        in_specs=(P(),),
        out_specs=specs,
        # check_vma=False everywhere in parallel/: see the contract note at
        # dp.py make_dp_train_step (fused_vjp local-partial grads) — pinned
        # by tests/test_parallel.py::test_check_vma_contract
        check_vma=False,
    )
    return jax.jit(fn)(params)


def place_opt_state(opt_state_flat, mesh: Mesh):
    """Places a flat-sharded opt-state tree onto the mesh: (n*chunk,) leaves
    split on 'data', scalars replicated."""
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P(DATA_AXIS) if getattr(x, "ndim", 0) >= 1 else P())
        ),
        opt_state_flat,
    )


# ---------------------------------------------------------------------------
# Gathered (params-shaped) <-> flat-sharded conversions.
#
# The CANONICAL external form of the optimizer state is params-shaped and
# replicated: checkpoints store it that way (chip-count portable — a run
# saved on 8 chips resumes on 256; multi-host saves need no cross-host
# device_get) and NAS rematerialization slices it with the same channel
# slicers as the params (nas/rematerialize.py). The flat (n*chunk,) sharded
# form exists only inside a live mesh.
# ---------------------------------------------------------------------------


def gather_opt_state(opt_state_flat, params):
    """Flat-sharded -> params-shaped replicated (jit-able on the mesh)."""
    from ..utils.treeutil import map_params_shaped

    pstruct = jax.tree.structure(params)

    def unflat(sub):
        return jax.tree.map(lambda f, p: f[: p.size].reshape(p.shape), sub, params)

    return map_params_shaped(opt_state_flat, pstruct, unflat)


def scatter_opt_state(opt_state_gathered, params, mesh: Mesh):
    """Params-shaped -> flat leaves sharded over THIS mesh (any size)."""
    from ..utils.treeutil import map_params_shaped

    n = mesh.size
    pstruct = jax.tree.structure(params)

    def flat(sub):
        return jax.tree.map(lambda x: _pad_flat(jnp.asarray(x), n), sub)

    return place_opt_state(map_params_shaped(opt_state_gathered, pstruct, flat), mesh)
