"""Distributed substrate: mesh, data-parallel steps, ZeRO sharded update."""

from .dp import make_dp_eval_step, make_dp_train_step, make_replica_sync_check
from .mesh import (
    DATA_AXIS,
    is_coordinator,
    local_batch_slice,
    make_mesh,
    prefetch_to_mesh,
    replicate,
    shard_batch,
)

__all__ = [
    "DATA_AXIS", "make_mesh", "shard_batch", "replicate", "prefetch_to_mesh",
    "local_batch_slice", "is_coordinator",
    "make_dp_train_step", "make_dp_eval_step", "make_replica_sync_check",
]
