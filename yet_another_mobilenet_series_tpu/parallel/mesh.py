"""Device mesh + sharding helpers (reference: utils/distributed.py init_dist /
rank helpers + apex DDP wrap, SURVEY.md §2 #12).

The reference's NCCL process-group world becomes a single SPMD program over a
1-D ``('data',)`` mesh: gradient allreduce and SyncBN moments ride ICI inside
the compiled step (SURVEY.md §5 "distributed communication backend"); DCN is
only involved across slices, handled transparently by the same collectives.
The serving engine (serve/engine.py) rides the same mesh for data-parallel
inference: params replicated, batch buckets sharded on 'data'.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import trace as obs_trace

DATA_AXIS = "data"


def make_mesh(num_devices: int = 0, devices=None) -> Mesh:
    """1-D data-parallel mesh. num_devices=0 → all visible devices."""
    devices = list(devices if devices is not None else jax.devices())
    if num_devices:
        if num_devices > len(devices):
            raise ValueError(f"requested {num_devices} devices, only {len(devices)} visible")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a host batch onto the mesh, split along the batch dimension.
    (The device_put_sharded step of SURVEY.md §3.1's TPU hot loop.)

    Single-host: a plain device_put. Multi-host: each process holds only its
    local rows (see local_batch_slice), so the global array is assembled with
    make_array_from_process_local_data — device_put to a sharding with
    non-addressable devices would fail.
    """
    s = batch_sharding(mesh)
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, s), batch)
    return jax.tree.map(lambda x: jax.make_array_from_process_local_data(s, np.asarray(x)), batch)


def replicate(tree, mesh: Mesh):
    s = replicated_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, s), tree)


def prefetch_to_mesh(batch_iter, mesh: Mesh, depth: int = 2):
    """Wraps a host batch iterator so device_put of the NEXT batch overlaps
    the CURRENT step's device compute (jax device_put is async). This is the
    prefetch-to-device stage of SURVEY.md §3.1's TPU hot loop — without it
    the chip idles for the H2D transfer every step. Each unit of ``depth``
    pins one global batch in device memory.

    Eager wrapper: depth validation (and the first transfers) happen at
    construction, not at the first next() deep inside the training loop.
    """
    import collections

    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    buf = collections.deque()

    def fill():
        # the span times host-side batch production + the async device_put
        # enqueue; a fat data/prefetch_fill next to a thin data/next means
        # the pipeline keeps up only because the prefetch depth hides it
        try:
            with obs_trace.get_tracer().span("data/prefetch_fill", "data"):
                buf.append(shard_batch(next(batch_iter), mesh))
            return True
        except StopIteration:
            return False

    for _ in range(depth):
        if not fill():
            break

    def gen():
        while buf:
            nxt = buf.popleft()
            fill()
            yield nxt

    return gen()


# --- multi-host glue (reference: is_master guards / master_only decorators) --


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """True on exactly one host — gates checkpoint writes and logging, like
    the reference's is_master()."""
    return jax.process_index() == 0


def local_batch_slice(global_batch: int, mesh: Mesh) -> int:
    """Per-host share of the global batch (per-host data sharding of the
    input pipeline, SURVEY.md §7 hard part 5)."""
    n_proc = jax.process_count()
    if global_batch % mesh.size:
        raise ValueError(f"global batch {global_batch} not divisible by {mesh.size} devices")
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    return global_batch // n_proc
