"""Data-parallel train/eval steps over the mesh (the apex-DDP replacement,
SURVEY.md §2 #12 and §3.1).

One ``jit(shard_map(step))`` per step: batch sharded on 'data', every state
pytree replicated. Gradients are pmean'd and BN moments psum'd *inside* the
program, so XLA overlaps the collectives with backprop the way apex's bucketed
allreduce overlapped with autograd — except scheduled by the compiler, not by
hand. Optionally the optimizer update itself is sharded across replicas and
the fresh params all-gathered (PAPERS.md:5, arXiv:2004.13336 — ZeRO-style
cross-replica weight-update sharding) to cut update time and optimizer memory.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..config import Config
from ..utils.compat import shard_map
from ..models.specs import Network
from ..train.steps import TrainState, make_eval_step, make_train_step
from .mesh import DATA_AXIS


def make_dp_train_step(
    net: Network,
    cfg: Config,
    optimizer,
    lr_fn: Callable,
    mesh: Mesh,
    *,
    penalty_fn=None,
    params_example=None,
    clip_shard_aware: bool = False,
):
    """jitted (ts, batch, rng) -> (ts, metrics) over the mesh.

    ts is fully replicated; batch is sharded on the 'data' axis. The per-shard
    rng is folded with the device's axis index so dropout/augment noise is
    decorrelated across replicas. With cfg.dist.shard_optimizer the optimizer
    accumulators are sharded on 'data' and the update runs ZeRO-style
    (parallel/zero.py).
    """
    shard_opt = cfg.dist.shard_optimizer
    sharded_update = None
    opt_spec = P()
    if shard_opt:
        if cfg.optim.grad_clip_norm > 0 and not clip_shard_aware:
            # a plain optax clip inside the ZeRO update would clip each
            # gradient SHARD by its own local norm (~global/sqrt(N)); the
            # caller must build the optimizer with
            # make_optimizer(..., shard_axis=DATA_AXIS) and attest it here
            raise ValueError(
                "grad_clip_norm with shard_optimizer requires an optimizer built with "
                "make_optimizer(..., shard_axis=DATA_AXIS); pass clip_shard_aware=True to attest"
            )
        from . import zero

        sharded_update = zero.make_zero_update(optimizer, mesh.size)
        if params_example is None:
            params_example, _ = jax.eval_shape(lambda: net.init(jax.random.PRNGKey(0)))
        opt_spec = zero.opt_state_specs(optimizer, params_example, mesh.size)
    inner = make_train_step(
        net, cfg, optimizer, lr_fn, axis_name=DATA_AXIS, penalty_fn=penalty_fn, sharded_update=sharded_update
    )
    if cfg.train.guard.enable:
        # device-side non-finite skip-and-rollback (train/guard.py). MUST
        # wrap inside the jit/donation boundary: the select reads the
        # pre-step buffers the compiled program donates.
        from ..train.guard import wrap_step_fn

        inner = wrap_step_fn(inner)

    def shard_fn(ts: TrainState, batch, rng):
        rng = jax.random.fold_in(rng, lax.axis_index(DATA_AXIS))
        return inner(ts, batch, rng)

    ts_spec = TrainState(
        step=P(), params=P(), state=P(), opt_state=opt_spec, ema_params=P(), ema_state=P(), masks=P(), rho_mult=P()
    )
    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(ts_spec, P(DATA_AXIS), P()),
        out_specs=(ts_spec, P()),
        # check_vma=False is LOAD-BEARING for bn_mode='fused_vjp': its
        # closed-form backward returns LOCAL partial dgamma/dbeta that the
        # step's pmean/psum_scatter combines (ops/layers.py
        # _bn_train_fused_bwd contract). Flipping to check_vma=True changes
        # shard_map's replication semantics — revisit that VJP first
        # (pinned by tests/test_parallel.py::test_check_vma_contract).
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0,))


def make_grouped_train_step(step_fn, k: int, event_fn=None):
    """ONE host dispatch running ``k`` sequential train steps: the jitted
    step inlines under trace, so the program is k unrolled step graphs
    back-to-back. Amortizes the per-step host-dispatch/tunnel latency that
    bench_bn's --dispatch-probe measures (PROFILE.md round 4) without any
    batch-stacking copy — each prefetched on-mesh batch is consumed in
    place, so data order, RNG folding (per-step via ts.step), and resume
    accounting are IDENTICAL to k single dispatches. Numerics agree to XLA
    fusion-boundary rounding (~1e-7 rel, measured: compiling k steps as one
    program lets XLA fuse across steps — NOT bit-identical, unlike remat;
    tests/test_parallel.py::test_grouped_step_equals_single_steps).

    event_fn (nas/masking.make_prune_event): applied after EVERY unrolled
    sub-step; its own (step % interval) & (step <= stop) gate makes
    off-cadence sub-steps a no-op, so AtomNAS search runs grouped with the
    mask/rho cadence identical to k single dispatches (VERDICT r4 next #4;
    tests/test_nas.py::test_grouped_search_step_equals_singles).

    Returns grouped(ts, (b_0..b_{k-1}), rng) -> (ts, [metrics_0..]).
    Compile time scales with k (unrolled); intended for small k (2-8)."""
    if k < 2:
        raise ValueError(f"grouped step needs k >= 2, got {k}")

    def grouped(ts: TrainState, batches, rng):
        out = []
        for b in batches:
            ts, metrics = step_fn(ts, b, rng)
            if event_fn is not None:
                masks, rho_mult = event_fn(ts.params, ts.masks, ts.rho_mult, ts.step)
                ts = ts.replace(masks=masks, rho_mult=rho_mult)
            out.append(metrics)
        return ts, out

    return jax.jit(grouped, donate_argnums=(0,))


def make_dp_eval_step(net: Network, cfg: Config, mesh: Mesh):
    """jitted (params, state, batch, masks) -> summed metric counts."""
    inner = make_eval_step(net, cfg, axis_name=DATA_AXIS)
    fn = shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(), P(), P(DATA_AXIS), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def make_replica_sync_check(mesh: Mesh):
    """Returns check(tree) -> max over leaves of max |leaf_i - leaf_0| across
    replicas (exactly 0.0 iff every replica is bit-identical).

    The distributed 'race detector' of SURVEY.md §5: replicated state must be
    bit-identical on every device; drift means non-deterministic compute or a
    broken collective. Per-leaf element-wise comparison — a summed scalar
    checksum in f32 rounds away small single-leaf divergence over millions of
    parameters. Run every cfg.train.param_checksum_every steps (debug knob;
    the all_gather per leaf is transient but not free).
    """

    def shard_fn(tree):
        worst = jnp.zeros((), jnp.float32)
        for l in jax.tree.leaves(tree):
            all_l = lax.all_gather(l.astype(jnp.float32), DATA_AXIS)
            worst = jnp.maximum(worst, jnp.max(jnp.abs(all_l - all_l[0])))
        return worst

    fn = shard_map(shard_fn, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False)
    return jax.jit(fn)
