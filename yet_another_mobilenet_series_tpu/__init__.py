"""yet_another_mobilenet_series_tpu: a TPU-native MobileNet/AtomNAS framework.

A from-scratch JAX/XLA rebuild of the capabilities of the public
``meijieru/yet_another_mobilenet_series`` (AtomNAS, ICLR'20) codebase:

- MobileNet V1/V2/V3 + MNASNet model zoo expressed as a block-spec grammar
  (SURVEY.md §3.4), built on a pure-functional NN core (``ops/``).
- AtomNAS one-shot search: FLOPs-weighted L1 on BatchNorm scales of atomic
  channel groups, with in-jit mask pruning and coarse-cadence shape
  rematerialization (``nas/``) — the XLA-friendly replacement for the
  reference's eager dynamic network shrinkage (SURVEY.md §3.2).
- Data-parallel training over a ``jax.sharding.Mesh`` with psum gradient
  allreduce and cross-replica SyncBN (``parallel/``) — replacing
  apex DDP + apex SyncBatchNorm + NCCL (SURVEY.md §2 #12).
- tf.data / native-C++ ImageNet input pipelines (``data/``, ``native/``) —
  replacing NVIDIA DALI.
- Orbax checkpointing with an architecture-spec sidecar (``ckpt/``).

The reference mount was empty this round (see SURVEY.md provenance warning);
behavioral parity targets come from SURVEY.md/BASELINE.md.
"""

__version__ = "0.1.0"
