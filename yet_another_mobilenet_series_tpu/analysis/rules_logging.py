"""YAMT007 — bare ``print(`` in package code.

The observability PR routed every runtime signal through one path — the
coordinator :class:`Logger` (+ module-level ``emit``), the obs registry, and
the span tracer — so "the run went quiet" is diagnosable from metrics.jsonl
instead of depending on which host's stdout a warning raced past. A bare
``print`` in package code silently forks that path again. This rule keeps it
closed.

Scope: only *package* code — files whose directory holds an ``__init__.py``
on disk. Standalone scripts, tests, and lint fixtures are exempt (a CLI
script's printed output IS its interface). Sanctioned surfaces inside the
package:

- ``utils/logging.py`` — the one place prints are the sink, by design;
- ``cli/profile.py`` and ``analysis/cli.py`` — report CLIs whose stdout is
  their product;
- any code under an ``if __name__ == "__main__":`` guard (module CLIs).

(Prints inside jit-traced functions are a different bug — YAMT001 — and are
flagged there; this rule is about host-side logging discipline.)
"""

from __future__ import annotations

import ast
import os

from .core import Finding, Project, Rule, SourceFile, register

# path suffixes (last two components) where print IS the output mechanism
_SANCTIONED = {"utils/logging.py", "cli/profile.py", "analysis/cli.py"}


def _is_main_guard(node: ast.If) -> bool:
    """``if __name__ == "__main__":`` (either comparison order)."""
    t = node.test
    if not (isinstance(t, ast.Compare) and len(t.ops) == 1 and isinstance(t.ops[0], ast.Eq)):
        return False
    sides = [t.left, t.comparators[0]]
    has_name = any(isinstance(s, ast.Name) and s.id == "__name__" for s in sides)
    has_main = any(isinstance(s, ast.Constant) and s.value == "__main__" for s in sides)
    return has_name and has_main


@register
class BarePrintInPackage(Rule):
    id = "YAMT007"
    name = "bare-print-in-package"
    description = (
        "bare print() in package code outside the sanctioned surfaces "
        "(utils/logging.py, cli/profile.py, analysis/cli.py, __main__ guards): "
        "route it through utils.logging.Logger/emit or the obs registry/tracer"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        path = src.path.replace(os.sep, "/")
        if "/".join(path.split("/")[-2:]) in _SANCTIONED:
            return []
        # package code only: a dir with __init__.py. Standalone scripts and
        # test/fixture trees print freely.
        if not os.path.exists(os.path.join(os.path.dirname(src.path), "__init__.py")):
            return []

        guarded: set[int] = set()
        for node in src.nodes:
            if isinstance(node, ast.If) and _is_main_guard(node):
                for sub in ast.walk(node):
                    guarded.add(id(sub))

        findings: list[Finding] = []
        for node in src.nodes:
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and id(node) not in guarded
            ):
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, self.id,
                        "bare print() in package code: route through "
                        "utils.logging.Logger/emit (or an obs registry counter) "
                        "so the signal reaches metrics.jsonl, not a random stdout",
                    )
                )
        return findings
