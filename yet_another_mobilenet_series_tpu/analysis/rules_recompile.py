"""YAMT009 — recompilation hazards (the first ROADMAP rule unblocked by the
interprocedural layer).

``jax.jit`` caches compiled programs by the HASH of every static argument and
by the values baked in at trace time. Two AST-visible ways to silently defeat
that cache, each costing a full recompile per training step (the exact
failure mode the per-epoch AtomNAS rebuild loop is most exposed to — there
the re-jit is intentional and paid at epoch cadence, not per step):

1. **Static-argument hazards at call sites.** A call to a jit-wrapped
   callable with ``static_argnums``/``static_argnames`` (resolved through
   the call graph: direct names, attribute calls, factory results) passing
   at a static position either a non-hashable literal (``[1, 2]`` — every
   call raises) or a freshly-constructed object (``Cfg(...)``,
   ``dict(...)``, ``np.array(...)``, a ``lambda`` — a new identity every
   call, so the cache NEVER hits and every step recompiles). The live
   contract this pins is ops/pallas_kernels.py's
   ``static_argnames=("stride", "act", "interpret")`` entry point: its
   callers must pass plain hashable values.

2. **Closure-captured values that vary per call.** A jitted function that
   reads a free variable which its enclosing scope rebinds AFTER the jit
   was created — or which is the loop variable of an enclosing loop
   containing the jitted def — bakes the trace-time value into the program:
   later calls silently keep the stale constant, and the "fix" of
   re-wrapping in the loop recompiles every iteration. (Rebinding BEFORE
   the jit exists — the ``forward = jax.checkpoint(forward)`` factory
   idiom in train/steps.py — is build-time setup and stays clean.)

3. **Module-level mutable globals read by jitted functions.** One scope up
   from (2): a jitted def (at any nesting) that reads a module-level global
   bound to a MUTABLE container (dict/list/set literal or constructor,
   ``defaultdict``/``deque``/…) which the module also mutates somewhere
   (subscript store/delete, a mutating method call, or ``global`` +
   rebind). The trace bakes the first-call contents into the program;
   every later mutation is silently ignored. Immutable globals and
   build-once-read-only tables stay clean — mutation evidence is required.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

_JIT_Q = {"jax.jit", "jax.pmap"}
_PARTIAL_Q = {"functools.partial", "partial"}

# constructors whose results hash by VALUE: passing them static is fine
_HASHABLE_BUILDERS = {"tuple", "frozenset", "str", "int", "float", "bool", "bytes", "complex", "range", "len"}
# builders that are fresh-per-call by construction (identity hash or unhashable)
_FRESH_NAMES = {"dict", "list", "set", "bytearray", "object"}
_FRESH_QUALIFIED = {
    "numpy.array",
    "numpy.asarray",
    "jax.numpy.array",
    "jax.numpy.asarray",
    "functools.partial",
}

_UNHASHABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp, ast.DictComp)
_MUTABLE_BUILDERS = {"dict", "list", "set", "bytearray"}
_MUTABLE_QUALIFIED = {"defaultdict", "OrderedDict", "deque", "Counter"}
_MUTATING_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "appendleft",
}


@register
class RecompilationHazard(Rule):
    id = "YAMT009"
    name = "recompilation-hazard"
    description = (
        "non-hashable or freshly-constructed values at static_argnums/static_argnames "
        "positions, or a jitted closure over a variable that varies per call: "
        "each silently recompiles (or stales) the program every step"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        out: dict[tuple, Finding] = {}
        self._check_static_call_sites(src, project, out)
        self._check_varying_closures(src, project, out)
        return list(out.values())

    # -- 1: static positions at resolved call sites -------------------------

    def _check_static_call_sites(self, src, project, out):
        cg = project.callgraph
        for call, scope, target in cg.resolved_calls(src):
            if target is None or target.kind != "jit" or not (target.static_nums or target.static_names):
                continue
            label = _call_label(call.func)
            inner_pos = (
                target.inner.func.pos_params
                if target.inner is not None and target.inner.kind == "function" and target.inner.func is not None
                else None
            )
            for i, arg in enumerate(call.args):
                is_static = i in target.static_nums or (
                    inner_pos is not None and i < len(inner_pos) and inner_pos[i] in target.static_names
                )
                if is_static:
                    self._flag_static_value(src, project, scope, arg, label, out)
            for kw in call.keywords:
                if kw.arg is not None and kw.arg in target.static_names:
                    self._flag_static_value(src, project, scope, kw.value, label, out)

    def _flag_static_value(self, src, project, scope, arg, label, out):
        def flag(msg):
            f = Finding(src.path, arg.lineno, arg.col_offset, self.id, msg)
            out.setdefault((f.path, f.line, f.col), f)

        if isinstance(arg, _UNHASHABLE_LITERALS):
            flag(
                f"non-hashable literal at a static position of '{label}': jit hashes "
                "static arguments, so every call fails (or falls back to retracing); "
                "pass a tuple/scalar or drop the static marking"
            )
        elif isinstance(arg, ast.Lambda):
            flag(
                f"lambda at a static position of '{label}': a fresh function object "
                "every call hashes by identity, so the jit cache never hits and every "
                "step recompiles; hoist it to a module-level def"
            )
        elif isinstance(arg, ast.Call):
            q = qualified_name(arg.func, src.aliases) or ""
            name = q.rsplit(".", 1)[-1]
            if q in _FRESH_QUALIFIED or (isinstance(arg.func, ast.Name) and arg.func.id in _FRESH_NAMES):
                fresh = True
            elif name in _HASHABLE_BUILDERS:
                fresh = False
            else:
                t = project.callgraph.resolve_expr(src, arg.func, scope)
                fresh = t is not None and t.kind == "class"
            if fresh:
                flag(
                    f"freshly-constructed object at a static position of '{label}': a new "
                    "object identity every call means a jit cache miss and a silent "
                    "recompile per step; construct it once outside the call"
                )

    # -- 2: closures over per-call-varying values ---------------------------

    def _check_varying_closures(self, src, project, out):
        symbols = project.symbols
        registrations: dict[int, tuple] = {}  # id(def node) -> (node, earliest jit line)

        def note(node, line):
            prev = registrations.get(id(node))
            registrations[id(node)] = (node, line if prev is None else min(prev[1], line))

        defs_by_name: dict[str, list] = {}
        for node in src.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)
                for dec in node.decorator_list:
                    q = qualified_name(dec.func if isinstance(dec, ast.Call) else dec, src.aliases)
                    if q in _JIT_Q:
                        note(node, dec.lineno)
                    elif isinstance(dec, ast.Call) and q in _PARTIAL_Q and dec.args:
                        if qualified_name(dec.args[0], src.aliases) in _JIT_Q:
                            note(node, dec.lineno)
        for node in src.nodes:
            if (
                isinstance(node, ast.Call)
                and qualified_name(node.func, src.aliases) in _JIT_Q
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                for d in defs_by_name.get(node.args[0].id, ()):
                    note(d, node.lineno)

        for fn_id, (root, reg_line) in registrations.items():
            fi = symbols.by_node.get(fn_id)
            if fi is None:
                continue
            for name in sorted(self._free_reads(root)):
                if fi.parent is None:
                    self._check_module_global(src, root, name, out)
                else:
                    self._check_free_name(src, root, fi, name, reg_line, out)

    @staticmethod
    def _free_reads(root) -> set[str]:
        bound: set[str] = set()
        reads: set[str] = set()
        for n in ast.walk(root):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                a = n.args
                bound |= {x.arg for x in (*a.posonlyargs, *a.args, *a.kwonlyargs)}
                bound |= {x.arg for x in (a.vararg, a.kwarg) if x is not None}
                if not isinstance(n, ast.Lambda):
                    bound.add(n.name)
            elif isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Load):
                    reads.add(n.id)
                else:
                    bound.add(n.id)
            elif isinstance(n, (ast.comprehension,)):
                pass
        return reads - bound

    def _check_free_name(self, src, root, fi, name, reg_line, out):
        scope_fi = fi.parent
        while scope_fi is not None:
            scope = scope_fi.node
            a = scope.args
            params = {x.arg for x in (*a.posonlyargs, *a.args, *a.kwonlyargs)} | {
                x.arg for x in (a.vararg, a.kwarg) if x is not None
            }
            loop_hit = self._loop_target_containing(scope, root, name)
            if loop_hit is not None:
                f = Finding(
                    src.path, root.lineno, root.col_offset, self.id,
                    f"jitted function '{getattr(root, 'name', '<lambda>')}' closes over "
                    f"'{name}', the loop variable of the enclosing loop at line "
                    f"{loop_hit}: every iteration re-wraps and recompiles (or bakes a "
                    "stale value); pass it as an argument or fold_in/static it",
                )
                out.setdefault((f.path, f.line, name), f)
                return
            late = self._assigned_after(scope, root, name, reg_line)
            if late is not None:
                f = Finding(
                    src.path, root.lineno, root.col_offset, self.id,
                    f"jitted function '{getattr(root, 'name', '<lambda>')}' closes over "
                    f"'{name}', reassigned at line {late} AFTER the jit was created: "
                    "the compiled program keeps the trace-time value (a re-jit would "
                    "recompile per call); pass it as an argument instead",
                )
                out.setdefault((f.path, f.line, name), f)
                return
            if name in params or self._binds(scope, root, name):
                return  # bound here, and none of the hazard shapes: clean
            scope_fi = scope_fi.parent
        # the scope chain never bound it: it's a module global
        self._check_module_global(src, root, name, out)

    # -- 3: module-level mutable globals ------------------------------------

    def _check_module_global(self, src, root, name, out):
        """A jitted function reading a module-level global bound to a mutable
        container that the module also mutates: the trace freezes the
        first-call contents. Mutation evidence is required — build-once
        lookup tables are the sanctioned module-constant idiom."""
        defn = None
        for st in src.tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                if st.name == name:
                    return
            elif isinstance(st, (ast.Import, ast.ImportFrom)):
                if any((a.asname or a.name.split(".")[0]) == name for a in st.names):
                    return
            elif isinstance(st, ast.Assign):
                if any(name in self._target_names(t) for t in st.targets):
                    defn = st
            elif isinstance(st, ast.AnnAssign):
                if isinstance(st.target, ast.Name) and st.target.id == name and st.value is not None:
                    defn = st
        if defn is None or not self._mutable_rhs(defn.value, src):
            return
        mut = self._mutation_line(src, name)
        if mut is None:
            return
        f = Finding(
            src.path, root.lineno, root.col_offset, self.id,
            f"jitted function '{getattr(root, 'name', '<lambda>')}' reads module-level "
            f"mutable global '{name}' (defined at line {defn.lineno}, mutated at line "
            f"{mut}): jit bakes the trace-time contents into the compiled program and "
            "silently ignores every later mutation; pass it as an argument or freeze "
            "it (tuple/frozenset) at module load",
        )
        out.setdefault((f.path, f.line, name), f)

    @staticmethod
    def _mutable_rhs(rhs, src) -> bool:
        if isinstance(rhs, _MUTABLE_LITERALS):
            return True
        if isinstance(rhs, ast.Call):
            q = qualified_name(rhs.func, src.aliases) or ""
            if q.rsplit(".", 1)[-1] in _MUTABLE_QUALIFIED:
                return True
            if isinstance(rhs.func, ast.Name) and rhs.func.id in _MUTABLE_BUILDERS:
                return True
        return False

    @staticmethod
    def _mutation_line(src, name) -> int | None:
        """Earliest line where the module mutates ``name`` in place: a
        subscript store/delete, a mutating method call, or a ``global``
        declaration (rebinding intent from inside a function)."""
        hits: list[int] = []
        for n in src.nodes:
            if (
                isinstance(n, ast.Subscript)
                and isinstance(n.value, ast.Name)
                and n.value.id == name
                and isinstance(n.ctx, (ast.Store, ast.Del))
            ):
                hits.append(n.lineno)
            elif (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == name
                and n.func.attr in _MUTATING_METHODS
            ):
                hits.append(n.lineno)
            elif isinstance(n, ast.Global) and name in n.names:
                hits.append(n.lineno)
        return min(hits) if hits else None

    @staticmethod
    def _loop_target_containing(scope, root, name) -> int | None:
        """Line of a for-loop in ``scope`` whose target binds ``name`` and
        whose body contains ``root``; None otherwise."""

        def walk(node, loops):
            if node is root:
                for lp in loops:
                    if name in RecompilationHazard._target_names(lp.target):
                        return lp.lineno
                return None
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
                and node is not scope
                and not _contains(node, root)
            ):
                return None  # a sibling scope: root isn't down this branch
            for child in ast.iter_child_nodes(node):
                nxt = loops + [node] if isinstance(node, (ast.For, ast.AsyncFor)) else loops
                hit = walk(child, nxt)
                if hit is not None:
                    return hit
            return None

        return walk(scope, [])

    @staticmethod
    def _target_names(t) -> set[str]:
        out: set[str] = set()
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.add(n.id)
        return out

    @staticmethod
    def _assigned_after(scope, root, name, reg_line) -> int | None:
        """Earliest assignment line of ``name`` in ``scope`` (nested defs
        excluded, other than the chain down to ``root``) strictly after the
        jit registration line."""
        hits: list[int] = []
        stack = [c for c in ast.iter_child_nodes(scope)]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    if name in RecompilationHazard._target_names(t) and n.lineno > reg_line:
                        hits.append(n.lineno)
            stack.extend(ast.iter_child_nodes(n))
        return min(hits) if hits else None

    @staticmethod
    def _binds(scope, root, name) -> bool:
        stack = [c for c in ast.iter_child_nodes(scope)]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                if any(name in RecompilationHazard._target_names(t) for t in targets):
                    return True
            elif isinstance(n, (ast.For, ast.AsyncFor)) and name in RecompilationHazard._target_names(n.target):
                return True
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if item.optional_vars is not None and name in RecompilationHazard._target_names(item.optional_vars):
                        return True
            stack.extend(ast.iter_child_nodes(n))
        return False


def _contains(node, target) -> bool:
    return any(n is target for n in ast.walk(node))


def _call_label(func: ast.expr) -> str:
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)) or "<call>"
