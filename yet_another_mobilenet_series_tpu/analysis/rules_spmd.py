"""SPMD-contract rules.

YAMT003 — collective axis names. ``lax.psum``/``pmean``/``axis_index``/...
over an axis name that no mesh defines fails only at trace time on a real
mesh (or worse, under a differently-named test mesh). The project's ground
truth is its module-level ``X_AXIS = "name"`` string constants
(``parallel/mesh.py`` ``DATA_AXIS``): literal axis strings must be one of
those values. Runtime-variable axis names (``axis_name=axis_name``
parameters) are unknowable statically and skipped.

YAMT004 — field-tuple/dataclass drift. A ``FOO_BAR_FIELDS = (...)`` tuple is
this codebase's idiom for "the checkpoint layout of dataclass FooBar"
(train/steps.py ``TRAIN_STATE_FIELDS`` <-> ``TrainState``). Adding a
dataclass field without updating the tuple silently drops state from every
checkpoint; this rule pins the two together across files.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

# collective -> positional index of the axis-name argument
_COLLECTIVES: dict[str, int] = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
    "jax.lax.axis_index": 0,
    "jax.lax.axis_size": 0,
}


@register
class CollectiveAxisName(Rule):
    id = "YAMT003"
    name = "collective-axis-name"
    description = (
        "lax.psum/pmean/axis_index/... with a literal axis name that no mesh-axis "
        "constant in the project defines (parallel/mesh.py DATA_AXIS is ground truth)"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        axes = project.axis_constants  # const name -> axis string
        if not axes:
            return []  # no ground truth in this project: nothing to validate
        known = ", ".join(sorted(set(axes.values())))
        findings: list[Finding] = []
        for node in src.nodes:
            if not isinstance(node, ast.Call):
                continue
            q = qualified_name(node.func, src.aliases)
            if q not in _COLLECTIVES:
                continue
            idx = _COLLECTIVES[q]
            axis_arg = None
            if len(node.args) > idx:
                axis_arg = node.args[idx]
            else:
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        axis_arg = kw.value
            if axis_arg is None:
                continue
            for bad in self._bad_axes(axis_arg, axes):
                findings.append(
                    Finding(
                        src.path, axis_arg.lineno, axis_arg.col_offset, self.id,
                        f"{q.rsplit('.', 1)[-1]} over unknown mesh axis '{bad}' "
                        f"(known axes: {known}); use the mesh-axis constant",
                    )
                )
        return findings

    def _bad_axes(self, node: ast.AST, axes: dict[str, str]) -> list[str]:
        """Literal axis names not defined by any project axis constant.
        Names/attributes are validated when they look like axis constants and
        skipped otherwise (runtime values)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [] if node.value in axes.values() else [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            bad = []
            for el in node.elts:
                bad.extend(self._bad_axes(el, axes))
            return bad
        return []  # runtime name/attribute: not statically checkable


def _camel(upper_snake: str) -> str:
    return "".join(w.capitalize() for w in upper_snake.split("_"))


def _is_dataclass(node: ast.ClassDef, aliases) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        q = qualified_name(target, aliases) or ""
        if "dataclass" in q.rsplit(".", 1)[-1]:
            return True
    return False


def _class_fields(node: ast.ClassDef) -> list[str]:
    return [st.target.id for st in node.body if isinstance(st, ast.AnnAssign) and isinstance(st.target, ast.Name)]


@register
class FieldTupleDrift(Rule):
    id = "YAMT004"
    name = "field-tuple-drift"
    description = (
        "a FOO_FIELDS tuple (checkpoint layout) that does not exactly match the "
        "fields of the Foo dataclass it mirrors (train/steps.py TRAIN_STATE_FIELDS contract)"
    )

    def check_project(self, project: Project) -> list[Finding]:
        # same-file class wins over a same-named class elsewhere in the tree
        by_file: dict[str, dict[str, list[str]]] = {}
        classes: dict[str, list[str]] = {}
        for src in project.files:
            local = by_file.setdefault(src.path, {})
            for node in src.nodes:
                if isinstance(node, ast.ClassDef) and _is_dataclass(node, src.aliases):
                    local.setdefault(node.name, _class_fields(node))
                    classes.setdefault(node.name, _class_fields(node))

        findings: list[Finding] = []
        for src in project.files:
            for node in src.tree.body:
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.endswith("_FIELDS")
                    and isinstance(node.value, (ast.Tuple, ast.List))
                ):
                    continue
                tname = node.targets[0].id
                elts = node.value.elts
                if not all(isinstance(e, ast.Constant) and isinstance(e.value, str) for e in elts):
                    continue
                listed = [e.value for e in elts]
                cls_name = _camel(tname[: -len("_FIELDS")])
                actual = by_file[src.path].get(cls_name, classes.get(cls_name))
                if actual is None or listed == actual:
                    continue
                missing = [f for f in actual if f not in listed]
                extra = [f for f in listed if f not in actual]
                detail = []
                if missing:
                    detail.append(f"missing {missing}")
                if extra:
                    detail.append(f"extra {extra}")
                if not detail:
                    detail.append(f"order differs (dataclass order: {actual})")
                findings.append(
                    Finding(
                        src.path, node.lineno, node.col_offset, self.id,
                        f"{tname} does not match dataclass {cls_name} fields: " + "; ".join(detail),
                    )
                )
        return findings
