"""Finding reporters: human text, machine JSON, and GitHub workflow
annotations (scripts/lint.sh --format github in CI)."""

from __future__ import annotations

import json
from typing import Sequence

from .core import Finding


def render_text(findings: Sequence[Finding]) -> str:
    """`path:line:col: RULE message` per finding plus a summary line."""
    lines = [f.format() for f in findings]
    n = len(findings)
    lines.append("clean: no findings" if n == 0 else f"{n} finding{'s' if n != 1 else ''}")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    """Stable JSON document: {"count": N, "findings": [{...}]}."""
    doc = {
        "count": len(findings),
        "findings": [
            {"path": f.path, "line": f.line, "col": f.col, "rule": f.rule, "message": f.message}
            for f in findings
        ],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def render_github(findings: Sequence[Finding]) -> str:
    """One ``::error`` workflow command per finding, so a GitHub Actions run
    annotates the offending line in the PR diff. Newlines inside messages
    are %-escaped per the workflow-command spec; a trailing plain summary
    line keeps the raw log readable."""

    def esc(s: str) -> str:
        return s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")

    lines = [
        f"::error file={f.path},line={f.line},col={f.col + 1},title={f.rule}::{esc(f.message)}"
        for f in findings
    ]
    n = len(findings)
    lines.append("clean: no findings" if n == 0 else f"{n} finding{'s' if n != 1 else ''}")
    return "\n".join(lines)
