"""YAMT006 — version-fragile jax imports.

``from jax import shard_map`` is exactly the one-line bug that broke all 5 of
the seed's tier-1 collection errors under jax 0.4.37 (shard_map only moved to
the top level in later releases); ``jax._src.*`` is private and reshuffles
every minor release; ``jax.experimental.maps`` (xmap) was deleted; and
``jax.experimental.shard_map`` is the OLD home, gone again in newer jax. The
resilient spellings are ``utils/compat.py`` (which resolves shard_map across
versions) or an explicit ``try/except ImportError`` version guard — imports
inside such a guard are exempt, since that IS the sanctioned idiom (it is how
utils/compat.py itself is written).
"""

from __future__ import annotations

import ast

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

_COMPAT = "utils/compat.py"
# `from jax import X` names that only exist in some jax versions
_FRAGILE_FROM_JAX = {
    "shard_map": f"moved across jax releases; import it from {_COMPAT}",
    "maps": "jax.experimental.maps (xmap) was removed from jax",
}
# fragile module prefixes for `import X` / `from X import ...`
_FRAGILE_MODULES = {
    "jax._src": "private jax internals, reshuffled every minor release",
    "jax.experimental.maps": "removed from jax (xmap is gone)",
    "jax.experimental.shard_map": f"old home of shard_map, removed in newer jax; use {_COMPAT}",
}
_GUARD_EXCEPTIONS = {"ImportError", "ModuleNotFoundError", "Exception", "AttributeError"}


def _module_matches(module: str) -> str | None:
    for prefix, why in _FRAGILE_MODULES.items():
        if module == prefix or module.startswith(prefix + "."):
            return why
    return None


@register
class FragileJaxImport(Rule):
    id = "YAMT006"
    name = "version-fragile-jax-import"
    description = (
        "an import that only resolves on some jax versions (from jax import shard_map, "
        "jax._src.*, jax.experimental.maps/shard_map) outside a try/except version guard"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        # imports anywhere inside a try/except that catches ImportError are
        # the sanctioned version-guard idiom (utils/compat.py) — exempt
        guarded: set[int] = set()
        for node in src.nodes:
            if not isinstance(node, ast.Try):
                continue
            catches = set()
            for h in node.handlers:
                t = h.type
                for n in t.elts if isinstance(t, ast.Tuple) else ([t] if t else []):
                    name = n.id if isinstance(n, ast.Name) else getattr(n, "attr", "")
                    catches.add(name)
            if not (catches & _GUARD_EXCEPTIONS) and not (None in [h.type for h in node.handlers]):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Import, ast.ImportFrom)):
                    guarded.add(id(sub))

        findings: list[Finding] = []

        def flag(node, what, why):
            findings.append(
                Finding(
                    src.path, node.lineno, node.col_offset, self.id,
                    f"version-fragile jax import `{what}`: {why}",
                )
            )

        for node in src.nodes:
            if isinstance(node, ast.Import) and id(node) not in guarded:
                for a in node.names:
                    why = _module_matches(a.name)
                    if why:
                        flag(node, f"import {a.name}", why)
            elif isinstance(node, ast.ImportFrom) and id(node) not in guarded and node.level == 0:
                mod = node.module or ""
                why = _module_matches(mod)
                if why:
                    flag(node, f"from {mod} import ...", why)
                elif mod == "jax":
                    for a in node.names:
                        if a.name in _FRAGILE_FROM_JAX:
                            flag(node, f"from jax import {a.name}", _FRAGILE_FROM_JAX[a.name])
                elif mod == "jax.experimental":
                    for a in node.names:
                        why = _module_matches(f"jax.experimental.{a.name}")
                        if why:
                            flag(node, f"from jax.experimental import {a.name}", why)
            elif isinstance(node, ast.Attribute):
                q = qualified_name(node, src.aliases)
                if q and _module_matches(q) and not isinstance(getattr(node, "ctx", None), ast.Store):
                    # flag only the full chain once: skip if the parent chain
                    # would also match (handled by dedupe below)
                    findings.append(
                        Finding(
                            src.path, node.lineno, node.col_offset, self.id,
                            f"version-fragile jax attribute access `{q}`: {_module_matches(q)}",
                        )
                    )
        # attribute chains yield one hit per sub-chain; keep one per location
        seen: set[tuple[int, int]] = set()
        out = []
        for f in findings:
            key = (f.line, f.col)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out
