"""YAMT013 — profiler capture windows without a finally-guaranteed stop.

``jax.profiler.start_trace`` opens a process-global capture; if the code
between start and ``stop_trace`` raises (a failed barrier sync, a chaos
injection, a preemption unwinding the loop), an unguarded window stays open:
every later dispatch keeps streaming into the trace, the dump never
finalizes, and on TPU a second ``start_trace`` then aborts the process. The
train CLI's profiler window is exactly this shape (cli/train.py) — the rule
pins the discipline that fixed it.

A ``start_trace`` call is GUARDED when a ``stop_trace`` call is reachable on
every exit path via a ``finally``:

- the start sits inside a ``try`` (body, else, or an except handler) whose
  ``finally`` contains a ``stop_trace`` call — possibly several levels up,
  but within the same function (a finally in a CALLER cannot be seen and is
  not credited); or
- the start is immediately followed, in the same statement block, by a
  ``try`` whose ``finally`` stops — the canonical ``start(); try: ...
  finally: stop()`` idiom (starting inside the try would risk stopping a
  never-started trace).

Split start/stop pairs that genuinely cannot share a frame (an HTTP-triggered
capture whose stop arrives as a separate request — obs/device.py
ProfilerCapture) carry a same-line suppression naming the out-of-band
guard, per the docs/LINT.md house rule.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, Rule, SourceFile, qualified_name, register


def _is_stop_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "stop_trace") or (
        isinstance(f, ast.Name) and f.id == "stop_trace"
    )


def _has_stop(stmts) -> bool:
    for st in stmts:
        for n in ast.walk(st):
            if _is_stop_call(n):
                return True
    return False


@register
class ProfilerStopGuard(Rule):
    id = "YAMT013"
    name = "profiler-window-unguarded"
    description = (
        "jax.profiler.start_trace without a finally-guaranteed stop_trace in the "
        "same function: an exception inside the capture window leaks the trace "
        "(and a later start_trace aborts on TPU) — wrap the window in try/finally"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        parents = src.parents
        findings: list[Finding] = []
        for node in src.nodes:
            if not isinstance(node, ast.Call):
                continue
            q = qualified_name(node.func, src.aliases) or ""
            if not (
                q.endswith("profiler.start_trace")
                or (isinstance(node.func, ast.Attribute) and node.func.attr == "start_trace")
            ):
                continue
            if self._guarded(node, parents):
                continue
            findings.append(Finding(
                src.path, node.lineno, node.col_offset, self.id,
                "jax.profiler.start_trace with no finally-guaranteed stop_trace: an "
                "exception inside the capture window leaks the trace — use "
                "`start_trace(...); try: ... finally: stop_trace()` (or suppress "
                "with the out-of-band guard named, for split start/stop pairs)",
            ))
        return findings

    def _guarded(self, call: ast.Call, parents: dict[int, ast.AST]) -> bool:
        # climb to each enclosing statement, checking both guard shapes at
        # every level; stop at the function boundary (a caller's finally is
        # invisible here and gets no credit)
        cur: ast.AST = call
        while True:
            parent = parents.get(id(cur))
            if parent is None or isinstance(
                parent, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module)
            ):
                # last chance: a module-/function-level start followed by a
                # guarded try in the same top-level block
                return self._followed_by_guarded_try(cur, parent)
            if isinstance(parent, ast.Try):
                field = next(
                    (
                        f
                        for f in ("body", "orelse", "finalbody")
                        if cur in getattr(parent, f)
                    ),
                    "handlers" if cur in parent.handlers else None,
                )
                if field in ("body", "orelse", "handlers") and _has_stop(parent.finalbody):
                    return True
            if isinstance(cur, ast.stmt) and self._followed_by_guarded_try(cur, parent):
                return True
            cur = parent

    def _followed_by_guarded_try(self, stmt: ast.AST, parent: ast.AST | None) -> bool:
        """``start_trace(...)`` then ``try: ... finally: stop_trace()`` as the
        next statement(s) of the same block."""
        if parent is None or not isinstance(stmt, ast.stmt):
            return False
        for field in ("body", "orelse", "finalbody"):
            block = getattr(parent, field, None)
            if isinstance(block, list) and stmt in block:
                after = block[block.index(stmt) + 1 :]
                return any(
                    isinstance(st, ast.Try) and _has_stop(st.finalbody) for st in after
                )
        return False
