"""Concurrency rules (YAMT019-021) on top of the thread-root/lock-domain
model in concurrency.py. All three are project rules: the hazards only
exist across function (and usually file) boundaries, and each finding lands
in the file containing the hazardous line so suppressions live next to the
code they document.

Scope matches YAMT011: package code only (a dir with ``__init__.py``) —
scripts and tests make throwaway threads whose lifetime is the process.
"""

from __future__ import annotations

from .concurrency import MAIN_REGION, is_package_code, short_lock
from .core import Finding, Project, Rule, register


def _no_common_lock(heldsets_a, heldsets_b) -> bool:
    """True when NO path pair protects both sides with a shared lock. Any
    overlapping pair silences the finding (toward silence on mixed paths)."""
    return not any(a & b for a in heldsets_a for b in heldsets_b)


def _mutually_exclusive(root_a, root_b) -> bool:
    """Thread roots spawned by DIFFERENT classes of the SAME inheritance
    family never coexist on one instance (a base-class loop and the subclass
    loop that replaces it): conflicts between them are not real."""
    return (
        root_a is not None
        and root_b is not None
        and root_a.spawner_cls != root_b.spawner_cls
        and root_a.spawner_family is not None
        and root_a.spawner_family == root_b.spawner_family
    )


def _region_label(region: str, root) -> str:
    return "main-thread code" if region == MAIN_REGION else root.label


def _setup_teardown(event, other_root) -> bool:
    """True when a main-region event lies inside the very function that
    spawns the other side's thread: writes there happen-before ``start()``
    (or follow ``join()``), the YAMT011-sanctioned setup/teardown shape."""
    if event[0] != MAIN_REGION or other_root is None or other_root.spawn_span is None:
        return False
    path, lo, hi = other_root.spawn_span
    return event[3] == path and lo <= event[4] <= hi


@register
class CrossThreadSharedState(Rule):
    id = "YAMT019"
    name = "cross-thread-shared-state"
    description = (
        "an attribute of a shared object is written in one thread region and "
        "read/written in another with no common lock held"
    )

    def check_project(self, project: Project) -> list[Finding]:
        model = project.concurrency
        out: list[Finding] = []
        for (family, attr), events in sorted(model.attr_events().items()):
            writes = [e for e in events if e[2] == "w"]
            if not writes:
                continue
            hit = None
            for w in writes:
                for e in events:
                    if e[0] == w[0]:
                        continue  # same region: program order, not a race
                    if w[0] == MAIN_REGION and e[0] == MAIN_REGION:
                        continue
                    if _mutually_exclusive(w[1], e[1]):
                        continue
                    if _setup_teardown(w, e[1]) or _setup_teardown(e, w[1]):
                        continue
                    if not _no_common_lock(w[5], e[5]):
                        continue
                    # prefer a thread-region write as the reported site
                    if hit is None or (hit[0][0] == MAIN_REGION and w[0] != MAIN_REGION):
                        hit = (w, e)
            if hit is None:
                continue
            w, e = hit
            if not is_package_code(w[3]):
                continue
            verb = "written" if e[2] == "w" else "read"
            out.append(
                Finding(
                    w[3], w[4], 0, self.id,
                    f"attribute '{attr}' of {family.rsplit('.', 1)[-1]} is written in "
                    f"{_region_label(w[0], w[1])} and {verb} in {_region_label(e[0], e[1])} "
                    f"(at {e[3]}:{e[4]}) with no common lock held; protect both sides with "
                    "one lock, or suppress with the lock-free idiom's reason (docs/LINT.md)",
                )
            )
        return out


@register
class LockOrderCycle(Rule):
    id = "YAMT020"
    name = "lock-order-cycle"
    description = "two locks are acquired in opposite orders on different paths (deadlock)"

    def check_project(self, project: Project) -> list[Finding]:
        model = project.concurrency
        edges, selfedges = model.lock_edges()
        out: list[Finding] = []

        for tok, (path, line) in sorted(selfedges.items()):
            if not is_package_code(path):
                continue
            out.append(
                Finding(
                    path, line, 0, self.id,
                    f"non-reentrant lock '{short_lock(tok)}' is acquired on a path that "
                    "already holds it: this self-deadlocks; use RLock or restructure "
                    "so the locked region never re-enters",
                )
            )

        # cycle detection on the acquired-while-holding graph: an edge A -> B
        # closes a cycle when some path of edges leads B back to A. Report
        # each cycle once, at the lexically smallest witness edge.
        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        reported: set[frozenset] = set()
        for (a, b), (path, line) in sorted(edges.items()):
            back = self._path(adj, b, a)  # [b, ..., a]
            if back is None:
                continue
            nodes = [a] + back[:-1]  # the distinct locks of the cycle
            key = frozenset(nodes)
            if key in reported or not is_package_code(path):
                continue
            reported.add(key)
            chain = " -> ".join(short_lock(t) for t in nodes + [a])
            opath, oline = edges[(back[-2], a)]
            out.append(
                Finding(
                    path, line, 0, self.id,
                    f"lock-order cycle: '{chain}'; the closing edge "
                    f"'{short_lock(back[-2])} -> {short_lock(a)}' is at {opath}:{oline}; "
                    "pick one acquisition order and use it everywhere",
                )
            )
        return out

    @staticmethod
    def _path(adj, start, goal):
        """Edge path [start, ..., goal] through ``adj``, or None."""
        stack, seen = [(start, [start])], {start}
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in sorted(adj.get(node, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


@register
class BlockingUnderContendedLock(Rule):
    id = "YAMT021"
    name = "blocking-under-contended-lock"
    description = (
        "a known-blocking call runs while holding a lock that other "
        "thread/main regions also take (the PR 8 compile-under-dispatch-lock bug)"
    )

    def check_project(self, project: Project) -> list[Finding]:
        model = project.concurrency
        acquire_regions = model.acquire_regions()
        out: list[Finding] = []
        for (desc, path, line), heldsets in sorted(model.blocking_sites().items()):
            if not is_package_code(path):
                continue
            contended = sorted(
                {
                    tok
                    for hs in heldsets
                    for tok in hs
                    if len(acquire_regions.get(tok, ())) >= 2
                }
            )
            if not contended:
                continue
            tok = contended[0]
            n = len(acquire_regions[tok])
            out.append(
                Finding(
                    path, line, 0, self.id,
                    f"blocking call {desc} runs while holding '{short_lock(tok)}', which "
                    f"{n} thread/main regions contend for: every waiter stalls behind this "
                    "call; move the slow work outside the lock (pre-compute, then take the "
                    "lock to publish) or suppress with the reason the stall is intended",
                )
            )
        return out
