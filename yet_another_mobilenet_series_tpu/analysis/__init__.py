"""yamt-lint: AST-based tracing-safety and SPMD-contract analysis.

The invariants that make train/steps.py compile to ONE XLA program over the
``('data',)`` mesh — no host effects under trace, disciplined PRNG key use,
collectives over real mesh axes, checkpoint-layout/dataclass agreement,
yml/config schema agreement, version-resilient jax imports — are all
detectable from source without importing it. This package detects them:
rules YAMT001-YAMT021 (see docs/LINT.md) over an interprocedural layer
(symbols.py project symbol table, callgraph.py call resolution, summaries.py
per-function dataflow summaries, concurrency.py thread-root/lock-domain
model — all pure AST), a suppression syntax plus a stale-suppression audit
(``--check-suppressions``), text/JSON/GitHub reporters, and a CLI
(``python -m yet_another_mobilenet_series_tpu.analysis``).

The tier-1 gate runs the analyzer over this package (tests/test_lint_clean.py),
so every invariant here is enforced on every PR.
"""

from .core import (
    Finding,
    Project,
    Rule,
    SourceFile,
    check_suppressions,
    load_rules,
    register,
    run_lint,
)
from .reporters import render_github, render_json, render_text

__all__ = [
    "Finding",
    "Project",
    "Rule",
    "SourceFile",
    "check_suppressions",
    "load_rules",
    "register",
    "render_github",
    "render_json",
    "render_text",
    "run_lint",
]
