"""yamt-lint: AST-based tracing-safety and SPMD-contract analysis.

The invariants that make train/steps.py compile to ONE XLA program over the
``('data',)`` mesh — no host effects under trace, disciplined PRNG key use,
collectives over real mesh axes, checkpoint-layout/dataclass agreement,
yml/config schema agreement, version-resilient jax imports — are all
detectable from source without importing it. This package detects them:
rules YAMT001-YAMT006 (see docs/LINT.md), a suppression syntax, text/JSON
reporters, and a CLI (``python -m yet_another_mobilenet_series_tpu.analysis``).

The tier-1 gate runs the analyzer over this package (tests/test_lint_clean.py),
so every invariant here is enforced on every PR.
"""

from .core import Finding, Project, Rule, SourceFile, load_rules, register, run_lint
from .reporters import render_json, render_text

__all__ = [
    "Finding",
    "Project",
    "Rule",
    "SourceFile",
    "load_rules",
    "register",
    "render_json",
    "render_text",
    "run_lint",
]
