"""YAMT010 — cross-call PRNG key reuse (YAMT002's call-graph gap).

YAMT002 tracks DIRECT ``jax.random`` draws, so ``net.init(rng)`` followed by
``sample(rng)`` was invisible: each callee consumes the key behind its own
``def``. With the interprocedural layer, every function's dataflow summary
(summaries.py) records which parameters it consumes as PRNG keys — including
transitively, and including ``split``/``fold_in`` (two callees splitting the
SAME key derive the SAME subkey streams). This rule replays YAMT002's
branch-aware linear flow, but a "consumption" is *passing the key whole to a
resolved callee whose matching parameter is key-consuming*: the second such
pass without an intervening rebind is correlated randomness across calls.

Deliberately NOT flagged:

- passing the same key to the SAME consuming callee across loop iterations —
  that is the sanctioned training-loop idiom (the step folds in ``ts.step``
  / the device axis index; cli/train.py), and unlike YAMT002's loop rule the
  callee is expected to derive its own per-call stream;
- passes to opaque callees (unresolvable targets never count — soundness
  over recall);
- one direct draw plus one callee pass (the direct half is YAMT002's beat;
  recorded as a known gap in docs/LINT.md).
"""

from __future__ import annotations

import ast

from .core import Finding, Project, Rule, SourceFile, register
from .rules_tracing import PRNGKeyReuse
from .summaries import summary_for_target


@register
class CrossCallKeyReuse(PRNGKeyReuse, Rule):
    id = "YAMT010"
    name = "cross-call-prng-key-reuse"
    description = (
        "a PRNG key passed whole to two or more callees whose dataflow summaries "
        "consume it (jax.random.*/split/fold_in, directly or transitively) without "
        "an intervening split/rebind: the callees derive correlated randomness"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        self._project = project
        self._first_sites: dict[str, str] = {}
        return super().check_file(src, project)

    # consumption = a whole-key pass to a resolved key-consuming callee;
    # overrides YAMT002's direct-draw counting (and drops its loop-depth
    # rule: same-callee-per-iteration is the sanctioned step idiom)
    def _check_draw(self, call, state, depth, src, out):
        cg = self._project.callgraph
        target = cg.resolve_call(src, call, self._scope)
        summary = summary_for_target(self._project, target)
        if summary is None or not summary.key_params:
            return
        bound = target.kind == "function" and target.bound
        label = _call_label(call.func)
        consumed: list[str] = []
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Name):
                pname = summary.param_at(i, bound)
                if pname is not None and pname in summary.key_params:
                    consumed.append(arg.id)
        for kw in call.keywords:
            if kw.arg in summary.key_params and isinstance(kw.value, ast.Name):
                consumed.append(kw.value.id)
        for name in consumed:
            ent = state.vars.get(name)
            if ent is None:
                state.vars[name] = [1, depth]
                self._first_sites.setdefault(name, f"'{label}' (line {call.lineno})")
                continue
            if ent[0] == 0:
                self._first_sites[name] = f"'{label}' (line {call.lineno})"
            ent[0] += 1
            if ent[0] == 2:
                first = self._first_sites.get(name, "an earlier callee")
                f = Finding(
                    src.path, call.lineno, call.col_offset, self.id,
                    f"PRNG key '{name}' passed whole to '{label}' after already being "
                    f"consumed whole by {first}: both callees derive the same random "
                    "streams — split the key (or fold_in a tag) per callee",
                )
                out.setdefault((f.line, name, self.id), f)


def _call_label(func: ast.expr) -> str:
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)) or "<call>"
