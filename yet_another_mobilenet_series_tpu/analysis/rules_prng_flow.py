"""YAMT010 — cross-call PRNG key reuse (YAMT002's call-graph gap).

YAMT002 tracks DIRECT ``jax.random`` draws, so ``net.init(rng)`` followed by
``sample(rng)`` was invisible: each callee consumes the key behind its own
``def``. With the interprocedural layer, every function's dataflow summary
(summaries.py) records which parameters it consumes as PRNG keys — including
transitively, and including ``split``/``fold_in`` (two callees splitting the
SAME key derive the SAME subkey streams). This rule replays YAMT002's
branch-aware linear flow, but a "consumption" is *passing the key whole to a
resolved callee whose matching parameter is key-consuming*: the second such
pass without an intervening rebind is correlated randomness across calls.

Deliberately NOT flagged:

- passing the same key to the SAME consuming callee across loop iterations —
  that is the sanctioned training-loop idiom (the step folds in ``ts.step``
  / the device axis index; cli/train.py), and unlike YAMT002's loop rule the
  callee is expected to derive its own per-call stream;
- passes to opaque callees (unresolvable targets never count — soundness
  over recall);
- two direct draws with no callee involved — that pair is exactly YAMT002's
  beat, and double-flagging one hazard under two ids helps nobody.

The MIXED pair — one direct draw plus one whole-key callee pass — lands
here: YAMT002 sees only one draw (count 1, silent) and the pure-callee rule
saw only one pass, so the pair slipped between the two rules (the gap
docs/LINT.md carried since PR 4). Direct draws now increment the same
per-name counter as callee passes, and the finding fires whenever the
second consumption involves at least one callee.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, Rule, SourceFile, qualified_name, register
from .rules_tracing import _KEY_SAFE, PRNGKeyReuse
from .summaries import summary_for_target


@register
class CrossCallKeyReuse(PRNGKeyReuse, Rule):
    id = "YAMT010"
    name = "cross-call-prng-key-reuse"
    description = (
        "a PRNG key passed whole to two or more callees whose dataflow summaries "
        "consume it (jax.random.*/split/fold_in, directly or transitively) without "
        "an intervening split/rebind: the callees derive correlated randomness"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        self._project = project
        self._first_sites: dict[str, str] = {}
        self._kinds: dict[str, list[str]] = {}
        return super().check_file(src, project)

    # consumption = a whole-key pass to a resolved key-consuming callee OR a
    # direct jax.random draw; overrides YAMT002's counting (and drops its
    # loop-depth rule: same-callee-per-iteration is the sanctioned step
    # idiom). A pair only flags when at least one half is a callee pass —
    # two direct draws stay YAMT002's finding.
    def _check_draw(self, call, state, depth, src, out):
        q = qualified_name(call.func, src.aliases)
        if q and q.startswith("jax.random."):
            fn = q.rsplit(".", 1)[-1]
            if fn in _KEY_SAFE or not call.args or not isinstance(call.args[0], ast.Name):
                return
            self._count(
                call.args[0].id, "direct",
                f"a direct jax.random.{fn} draw (line {call.lineno})",
                call, state, depth, src, out,
            )
            return
        cg = self._project.callgraph
        target = cg.resolve_call(src, call, self._scope)
        summary = summary_for_target(self._project, target)
        if summary is None or not summary.key_params:
            return
        bound = target.kind == "function" and target.bound
        label = _call_label(call.func)
        consumed: list[str] = []
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Name):
                pname = summary.param_at(i, bound)
                if pname is not None and pname in summary.key_params:
                    consumed.append(arg.id)
        for kw in call.keywords:
            if kw.arg in summary.key_params and isinstance(kw.value, ast.Name):
                consumed.append(kw.value.id)
        for name in consumed:
            self._count(
                name, "callee", f"'{label}' (line {call.lineno})",
                call, state, depth, src, out,
            )

    def _count(self, name, kind, site, call, state, depth, src, out):
        kinds = self._kinds.setdefault(name, [])
        ent = state.vars.get(name)
        if ent is None:
            state.vars[name] = [1, depth]
            kinds.append(kind)
            self._first_sites.setdefault(name, site)
            return
        if ent[0] == 0:
            # fresh rebind: the old consumption stream is closed
            self._first_sites[name] = site
            kinds.clear()
        kinds.append(kind)
        ent[0] += 1
        if ent[0] == 2 and "callee" in kinds:
            first = self._first_sites.get(name, "an earlier consumer")
            if kind == "callee":
                msg = (
                    f"PRNG key '{name}' passed whole to {site} after already being "
                    f"consumed by {first}: the callee re-derives the same random "
                    "streams — split the key (or fold_in a tag) per consumer"
                )
            else:
                msg = (
                    f"PRNG key '{name}' consumed by {site} after already being "
                    f"passed whole to {first}: the draw repeats the callee's "
                    "stream — split the key (or fold_in a tag) per consumer"
                )
            f = Finding(src.path, call.lineno, call.col_offset, self.id, msg)
            out.setdefault((f.line, name, self.id), f)


def _call_label(func: ast.expr) -> str:
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)) or "<call>"
