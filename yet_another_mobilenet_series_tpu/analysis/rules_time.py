"""YAMT017 — wall-clock durations: ``time.time()`` differenced in package
code.

``time.time()`` reads the WALL clock: NTP slews and steps it, operators and
VMs jump it, leap smears bend it. A timestamp read from it is fine — that is
what it is for — but the moment two readings are SUBTRACTED the result is a
duration measured with a ruler that changes length, and this repo's serving
stack is built out of exactly the code where that corrupts behavior:
timeouts, retry backoff, breaker cooldowns, hedge timers, poll schedules,
latency histograms. A backward NTP step can re-arm a cooldown forever; a
forward step fires every deadline at once. The sanctioned idiom is
``time.monotonic()`` (or ``time.perf_counter()`` for fine measurement) —
guaranteed non-decreasing, which is the property every duration needs.

Flagged (package code only — a directory holding ``__init__.py`` — like
YAMT007/011/012):

- a subtraction where either operand is a ``time.time()`` call or a local
  name assigned from one (``t0 = time.time(); ...; time.time() - t0``);
- comparisons against a wall-clock DEADLINE: a name assigned from
  ``time.time() + x`` (or augmented ``+=``) compared to ``time.time()``
  or to another tainted name (``while time.time() < deadline:``).

Deliberately NOT flagged:

- ``time.time()`` stored, logged, or shipped as a TIMESTAMP (the
  ``_PROC_START_UNIX`` identity field, provenance stamps, artifact rows):
  the hazard is subtraction, not the reading;
- ``time.monotonic()`` / ``time.perf_counter()`` arithmetic — the fix;
- cross-process comparisons of wall timestamps for EQUALITY/identity
  (restart detection compares ``start_unix`` values, never differences
  them into a duration).

Intentional wall-clock durations (rare: log-file age math against mtimes)
carry a same-line suppression with a WHY comment (docs/LINT.md house
rule)::

    age = time.time() - mtime  # yamt-lint: disable=YAMT017 — mtime IS wall clock
"""

from __future__ import annotations

import ast
import os

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

# the wall clock; datetime.now() family deliberately out of scope (never
# used for durations in this repo — revisit if it appears)
_WALL = ("time.time",)


def _is_wall_call(node: ast.AST, aliases: dict[str, str]) -> bool:
    return (
        isinstance(node, ast.Call)
        and not node.args
        and not node.keywords
        and qualified_name(node.func, aliases) in _WALL
    )


class _ScopeTaint(ast.NodeVisitor):
    """Per-scope (module / function) taint walk in source order.

    ``stamps`` are names holding a raw wall-clock reading; ``deadlines``
    are names holding wall-clock arithmetic (``time.time() + x``). Both
    taint through reassignment and augmented assignment; any other
    assignment to the name clears it (linear flow, the repo's idiom — the
    rules_async_staging trade-off: simple and predictable beats a full
    dataflow lattice for a lint gate)."""

    def __init__(self, src: SourceFile, rule_id: str):
        self.src = src
        self.rule_id = rule_id
        self.stamps: set[str] = set()
        self.deadlines: set[str] = set()
        self.findings: list[Finding] = []

    # -- taint sources -------------------------------------------------------

    def _tainted(self, node: ast.AST) -> bool:
        """Wall-clock VALUE: a direct call or a stamp/deadline name."""
        if _is_wall_call(node, self.src.aliases):
            return True
        return isinstance(node, ast.Name) and (
            node.id in self.stamps or node.id in self.deadlines
        )

    def _value_taint(self, value: ast.AST) -> str | None:
        """'stamp' / 'deadline' / None for one assigned value."""
        if _is_wall_call(value, self.src.aliases):
            return "stamp"
        if isinstance(value, ast.Name):
            if value.id in self.stamps:
                return "stamp"
            if value.id in self.deadlines:
                return "deadline"
            return None
        if isinstance(value, ast.BinOp) and isinstance(value.op, (ast.Add, ast.Sub)):
            # time.time() + x / stamp + x: a wall-clock deadline. (A Sub of
            # two tainted values is flagged as a duration where it OCCURS;
            # the assigned name still carries deadline taint so later
            # comparisons keep flagging.)
            if self._tainted(value.left) or self._tainted(value.right):
                return "deadline"
        return None

    def _assign_name(self, name: str, value: ast.AST) -> None:
        taint = self._value_taint(value)
        self.stamps.discard(name)
        self.deadlines.discard(name)
        if taint == "stamp":
            self.stamps.add(name)
        elif taint == "deadline":
            self.deadlines.add(name)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)  # flag expressions inside the value first
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                self._assign_name(tgt.id, node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self.generic_visit(node)
        if node.value is not None and isinstance(node.target, ast.Name):
            self._assign_name(node.target.id, node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if isinstance(node.target, ast.Name) and isinstance(node.op, (ast.Add, ast.Sub)):
            name = node.target.id
            # deadline += gap keeps deadline taint; t0 += x stays a stamp-ish
            # wall value; adding a wall value to a clean name taints it
            if name in self.stamps or name in self.deadlines or self._tainted(node.value):
                self.stamps.discard(name)
                self.deadlines.add(name)

    # -- hazards -------------------------------------------------------------

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            self.src.path, node.lineno, node.col_offset, self.rule_id,
            f"{what}: time.time() is the WALL clock — NTP steps corrupt the "
            "difference; use time.monotonic() (or time.perf_counter()) for "
            "durations, deadlines, timeouts, and backoff",
        ))

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, ast.Sub) and (
            self._tainted(node.left) or self._tainted(node.right)
        ):
            self._flag(node, "wall-clock duration (subtraction of time.time() readings)")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        operands = [node.left, *node.comparators]
        ordered = any(isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE)) for op in node.ops)
        if ordered and sum(1 for o in operands if self._tainted(o)) >= 2:
            # time.time() < deadline / t_now >= t_deadline: an ordering
            # comparison of two wall readings IS a duration in disguise.
            # (Equality against a recorded start_unix is identity, not a
            # duration — not flagged.)
            self._flag(node, "wall-clock deadline comparison")
        self.generic_visit(node)

    # nested functions get their own scope walk (run by the rule), so stop
    # descending into them from the enclosing scope
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass


@register
class WallClockDuration(Rule):
    id = "YAMT017"
    name = "wall-clock-duration"
    description = (
        "time.time() readings subtracted or deadline-compared in package "
        "code: wall-clock durations jump with NTP steps — use "
        "time.monotonic()/perf_counter() for timeouts, backoff, and latency"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        # package code only: a dir with __init__.py (scripts/tests exempt)
        if not os.path.exists(os.path.join(os.path.dirname(src.path), "__init__.py")):
            return []
        findings: list[Finding] = []
        scopes: list[ast.AST] = [src.tree]
        for node in src.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                scopes.append(node)
        for scope in scopes:
            walker = _ScopeTaint(src, self.id)
            body = scope.body if not isinstance(scope, ast.Lambda) else [ast.Expr(scope.body)]
            for stmt in body:
                walker.visit(stmt)
            findings.extend(walker.findings)
        return findings
