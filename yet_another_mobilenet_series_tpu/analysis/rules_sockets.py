"""YAMT018 — sockets without an explicit timeout in package code.

A socket with no timeout blocks FOREVER, and "forever" is exactly what a
partitioned network delivers: a blackholed peer accepts the handshake and
then says nothing, a half-open socket ACKs and never answers, a dead NAT
entry eats the response. Every one of those turns a blocking ``recv`` /
``connect`` into a wedged thread — the hang class serve/netchaos.py exists
to inject and the connect/read timeout split exists to contain. The
sanctioned idiom is an EXPLICIT bound on every socket the package opens:
the operator chose a budget, whatever it is.

Flagged (package code only — a directory holding ``__init__.py`` — like
YAMT007/011/012/017):

- ``socket.create_connection(addr)`` without a timeout (second positional
  argument or ``timeout=`` keyword);
- ``http.client.HTTPConnection(...)`` / ``HTTPSConnection(...)`` without a
  ``timeout=`` keyword (the stdlib default is ``None`` = block forever);
- ``socket.socket(...)`` whose result never receives a ``.settimeout(...)``
  (or ``.setblocking(False)`` — the non-blocking idiom) in the same scope:
  tracked through plain-name and ``self.attr`` assignments and ``with``
  targets, linear flow like the other scope-walk rules. An unassigned
  ``socket.socket()`` call (passed straight into something else) is flagged
  — the timeout cannot be proven from here.

Deliberately NOT flagged:

- an explicit ``timeout=None`` — the operator SAID forever, loudly; the
  rule polices silent defaults, not deliberate choices;
- sockets the stdlib hands back already bounded by their owner
  (``accept()`` results, ``ThreadingHTTPServer`` internals): only
  constructor calls are in scope;
- scripts/ and tests/ (not package code) — benches own their budgets.

Intentional unbounded sockets carry a same-line suppression with a WHY
comment (docs/LINT.md house rule)::

    s = socket.socket()  # yamt-lint: disable=YAMT018 — lifetime-bounded by X
"""

from __future__ import annotations

import ast
import os

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

_CREATE_CONN = ("socket.create_connection",)
_HTTP_CONNS = ("http.client.HTTPConnection", "http.client.HTTPSConnection")
_SOCKET_CTOR = ("socket.socket",)


def _has_timeout_kw(node: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in node.keywords)


def _target_path(node: ast.AST) -> str | None:
    """'name' or 'self.attr' for assignment/with targets we can track."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)):
        return f"{node.value.id}.{node.attr}"
    return None


class _ScopeWalk(ast.NodeVisitor):
    """One scope's socket bookkeeping: socket.socket() calls assigned to
    trackable targets, and the settimeout/setblocking calls that sanction
    them. Linear flow, no dataflow lattice — the repo's scope-walk idiom."""

    def __init__(self, src: SourceFile, rule_id: str):
        self.src = src
        self.rule_id = rule_id
        self.findings: list[Finding] = []
        # target path -> the socket() Call node awaiting a settimeout
        self.pending: dict[str, ast.Call] = {}

    def _is_socket_ctor(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and qualified_name(node.func, self.src.aliases) in _SOCKET_CTOR)

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            self.src.path, node.lineno, node.col_offset, self.rule_id,
            f"{what}: a socket with no timeout blocks forever on a partitioned "
            "peer (blackhole / half-open) — set an explicit bound "
            "(settimeout(...), timeout=..., or a deliberate timeout=None)",
        ))

    # -- constructor sites ----------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        qn = qualified_name(node.func, self.src.aliases)
        if qn in _CREATE_CONN and len(node.args) < 2 and not _has_timeout_kw(node):
            self._flag(node, "socket.create_connection without a timeout")
        elif qn in _HTTP_CONNS and not _has_timeout_kw(node):
            self._flag(node, f"{qn.rsplit('.', 1)[1]} without timeout= "
                             "(the stdlib default blocks forever)")
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if not self._is_socket_ctor(node.value):
            return
        tracked = False
        for tgt in node.targets:
            path = _target_path(tgt)
            if path is not None:
                self.pending[path] = node.value
                tracked = True
        if not tracked:
            self._flag(node.value, "socket.socket() result untracked")

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            if self._is_socket_ctor(item.context_expr):
                path = _target_path(item.optional_vars) if item.optional_vars else None
                if path is not None:
                    self.pending[path] = item.context_expr
                else:
                    self._flag(item.context_expr, "socket.socket() in a with block")
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        # <target>.settimeout(...) / <target>.setblocking(False) sanctions
        # the pending socket on that target
        call = node.value
        if (isinstance(call, ast.Call) and isinstance(call.func, ast.Attribute)
                and call.func.attr in ("settimeout", "setblocking")):
            path = _target_path(call.func.value)
            if path is not None:
                self.pending.pop(path, None)
        self.generic_visit(node)

    # nested scopes run their own walk (the rule drives them), so stop here
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def flush(self) -> None:
        for call in self.pending.values():
            self._flag(call, "socket.socket() never given a timeout in this scope")


@register
class SocketWithoutTimeout(Rule):
    id = "YAMT018"
    name = "socket-without-timeout"
    description = (
        "socket.socket()/create_connection/HTTPConnection without an explicit "
        "timeout in package code: unbounded sockets wedge threads on "
        "partitioned peers — set an explicit bound"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        # package code only: a dir with __init__.py (scripts/tests exempt)
        if not os.path.exists(os.path.join(os.path.dirname(src.path), "__init__.py")):
            return []
        findings: list[Finding] = []
        scopes: list[ast.AST] = [src.tree]
        for node in src.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node)
        for scope in scopes:
            walker = _ScopeWalk(src, self.id)
            for stmt in scope.body:
                walker.visit(stmt)
            walker.flush()
            findings.extend(walker.findings)
        return findings
