"""Intra-package call resolution on top of the symbol table (symbols.py).

Resolves, purely from the AST, what a call expression refers to:

- direct calls to module functions, including through import aliases
  (``from .core import helper as h`` / ``eng.helper(...)``);
- method calls on locally-constructed instances (``trainer = Trainer(...);
  trainer.train_step(...)``) and on parameters annotated with a project
  class, plus ``self.method(...)`` / ``self._fn(...)`` inside methods
  (``self._fn = ...`` assignments are read from the class body);
- ``jax.jit``/``jax.pmap``/``functools.partial(jax.jit, ...)`` wrappers,
  carrying their static ``donate_argnums``/``static_argnums``/
  ``static_argnames`` and the wrapped callable;
- call-result bindings through function summaries (``step =
  make_train_step(...)`` resolves to the inner ``step_fn`` that
  ``make_train_step`` returns — summaries.py computes ``returns``);
- values threaded through LITERAL containers and same-length tuple
  unpacking (``fwd, bwd = make_fwd, make_bwd`` then ``fwd(...)``;
  ``steps = (init, apply); steps[1](...)``; constant-keyed dict literals) —
  the container must be a literal visible in the scope chain, and the
  index/key a constant.

Anything else — ``getattr`` chains, containers built by calls or mutated
after construction, computed indices — degrades to *opaque* (``None``),
never a crash or a guess: every interprocedural rule must stay sound when
resolution gives up.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .core import qualified_name
from .symbols import ClassInfo, FunctionInfo, ModuleInfo

_JIT_WRAPPERS = {"jax.jit", "jax.pmap"}
_PARTIAL = {"functools.partial", "partial"}


@dataclasses.dataclass
class Target:
    """What an expression resolves to. ``kind`` is one of ``function``
    (a project def; ``bound`` when reached through an instance), ``class``
    (a project class, i.e. a constructor), ``instance`` (a value known to be
    an instance of a project class), ``module``, or ``jit`` (a
    jax.jit/jax.pmap-wrapped callable with its static call contract)."""

    kind: str
    func: Optional[FunctionInfo] = None
    cls: Optional[ClassInfo] = None
    mod: Optional[ModuleInfo] = None
    inner: Optional["Target"] = None  # kind == 'jit': the wrapped callable
    donate: tuple[int, ...] = ()
    static_nums: tuple[int, ...] = ()
    static_names: tuple[str, ...] = ()
    bound: bool = False


def _int_tuple(node: ast.expr) -> tuple[int, ...] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)) and all(
        isinstance(e, ast.Constant) and isinstance(e.value, int) for e in node.elts
    ):
        return tuple(e.value for e in node.elts)
    return None


def _str_tuple(node: ast.expr) -> tuple[str, ...] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)) and all(
        isinstance(e, ast.Constant) and isinstance(e.value, str) for e in node.elts
    ):
        return tuple(e.value for e in node.elts)
    return None


class CallGraph:
    """Lazy resolver over one :class:`core.Project`. Scope environments are
    cached; target resolution is recomputed on demand (it may sharpen as the
    summary fixpoint fills in)."""

    def __init__(self, project):
        self.project = project
        self.symbols = project.symbols
        self._envs: dict = {}
        self._cache: dict[tuple, Optional[Target]] = {}
        self._summary_reads = 0

    # -- scope bookkeeping --------------------------------------------------

    def enclosing_scope(self, src, node: ast.AST):
        """Nearest enclosing FunctionDef/AsyncFunctionDef of ``node`` in
        ``src`` (None = module scope) — served from the SourceFile's
        one-time DFS index rather than a per-file recursion here."""
        return src.scopes.get(id(node))

    def _scope_chain(self, src, scope_node):
        chain = []
        node = scope_node
        while node is not None:
            chain.append(node)
            fi = self.symbols.by_node.get(id(node))
            node = fi.parent.node if fi is not None and fi.parent is not None else None
        return chain

    def _env(self, src, scope_node):
        key = (src.path, id(scope_node) if scope_node is not None else None)
        env = self._envs.get(key)
        if env is not None:
            return env
        env = {}
        self._envs[key] = env  # registered first: annotation resolution below re-enters
        raw = src.tree.body if scope_node is None else scope_node.body
        body = raw if isinstance(raw, list) else []  # a Lambda's body is an expression
        if scope_node is not None and not isinstance(scope_node, ast.Lambda):
            fi = self.symbols.by_node.get(id(scope_node))
            if fi is not None and fi.cls is not None and fi.pos_params:
                env[fi.pos_params[0]] = ("instance", fi.cls)
            for arg in (
                *scope_node.args.posonlyargs, *scope_node.args.args, *scope_node.args.kwonlyargs
            ):
                if arg.annotation is not None:
                    # annotations name module-level classes; resolving them
                    # against the (still-building) local scope would recurse
                    t = self.resolve_expr(src, arg.annotation, None)
                    if t is not None and t.kind == "class":
                        env.setdefault(arg.arg, ("instance", t.cls))
        self._fill_env(env, body, src)
        return env

    def _fill_env(self, env, stmts, src):
        """Shallow binding prepass over one scope: nested defs/classes bind
        their names; every other assignment target binds its RHS (or opaque
        when unresolvable/conflicting) so inner scopes can't leak through."""
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = self.symbols.by_node.get(id(st))
                self._bind(env, st.name, ("def", fi) if fi is not None else ("opaque", None))
            elif isinstance(st, ast.ClassDef):
                self._bind(env, st.name, ("opaque", None))  # local classes: rare, skip
            elif isinstance(st, ast.Assign) and len(st.targets) == 1 and isinstance(st.targets[0], ast.Name):
                self._bind(env, st.targets[0].id, ("expr", st.value))
            elif (
                isinstance(st, ast.Assign)
                and len(st.targets) == 1
                and isinstance(st.targets[0], (ast.Tuple, ast.List))
                and isinstance(st.value, (ast.Tuple, ast.List))
                and len(st.targets[0].elts) == len(st.value.elts)
                and not any(isinstance(e, ast.Starred) for e in st.value.elts)
            ):
                # same-length literal tuple unpack: elementwise bindings
                for tgt, val in zip(st.targets[0].elts, st.value.elts):
                    if isinstance(tgt, ast.Name):
                        self._bind(env, tgt.id, ("expr", val))
                    else:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                self._bind(env, n.id, ("opaque", None))
            else:
                for t in self._assigned_names(st):
                    self._bind(env, t, ("opaque", None))
                for block in ("body", "orelse", "finalbody"):
                    self._fill_env(env, getattr(st, block, []), src)
                for h in getattr(st, "handlers", []):
                    self._fill_env(env, h.body, src)

    @staticmethod
    def _assigned_names(st) -> list[str]:
        out = []

        def targets(t):
            if isinstance(t, ast.Name):
                out.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    targets(el)
            elif isinstance(t, ast.Starred):
                targets(t.value)

        if isinstance(st, ast.Assign):
            for t in st.targets:
                targets(t)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            targets(st.target)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            targets(st.target)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                if item.optional_vars is not None:
                    targets(item.optional_vars)
        return out

    def _bind(self, env, name, binding):
        if name in env and env[name] != binding:
            prev = env[name]
            same = (
                prev[0] == binding[0] == "expr"
                and ast.dump(prev[1]) == ast.dump(binding[1])
            )
            if not same:
                env[name] = ("opaque", None)
            return
        env[name] = binding

    # -- resolution ---------------------------------------------------------

    def resolve_call(self, src, call: ast.Call, scope_node=None) -> Optional[Target]:
        return self.resolve_expr(src, call.func, scope_node)

    def resolve_expr(self, src, expr: ast.expr, scope_node=None, _guard=None) -> Optional[Target]:
        """Resolve an expression to a :class:`Target`, or None (opaque)."""
        if _guard is None:
            # memoize top-level resolutions, but only once the summaries
            # fixpoint has converged: mid-fixpoint results sharpen as
            # ``returns`` entries land, and caching them would freeze the
            # weaker answer (AST node ids are stable: the Project owns every
            # tree for its whole lifetime)
            key = (id(expr), id(scope_node) if scope_node is not None else None)
            if key in self._cache:
                return self._cache[key]
            before = self._summary_reads
            result = self.resolve_expr(src, expr, scope_node, set())
            # a resolution whose descent never consulted a summary's
            # ``returns`` depends only on static structure (symbols, env
            # bindings) and cannot sharpen — cache it mid-fixpoint too
            if getattr(self.project, "_summaries_done", False) or self._summary_reads == before:
                self._cache[key] = result
            return result
        if id(expr) in _guard:
            return None
        _guard.add(id(expr))

        if isinstance(expr, ast.Name):
            return self._resolve_name(src, expr.id, scope_node, _guard)
        if isinstance(expr, ast.Attribute):
            base = self.resolve_expr(src, expr.value, scope_node, _guard)
            if base is None:
                return None
            return self._member(base, expr.attr, _guard)
        if isinstance(expr, ast.Call):
            return self._resolve_call_result(src, expr, scope_node, _guard)
        if isinstance(expr, ast.Subscript):
            return self._resolve_subscript(src, expr, scope_node, _guard)
        return None

    def _resolve_subscript(self, src, sub: ast.Subscript, scope_node, _guard):
        """``container[const]`` where the container chases (through Name
        bindings) to a literal Tuple/List/Dict: resolve the selected element.
        Mutated-after-construction containers never get here — any second
        binding of the name went opaque in ``_bind``."""
        if not isinstance(sub.slice, ast.Constant):
            return None
        got = self._literal_container(src, sub.value, scope_node)
        if got is None:
            return None
        cont, csrc, cscope = got
        idx = sub.slice.value
        if isinstance(cont, (ast.Tuple, ast.List)):
            if (
                isinstance(idx, int)
                and not isinstance(idx, bool)
                and -len(cont.elts) <= idx < len(cont.elts)
                and not any(isinstance(e, ast.Starred) for e in cont.elts)
            ):
                return self.resolve_expr(csrc, cont.elts[idx], cscope, _guard)
            return None
        if isinstance(cont, ast.Dict):
            for k, v in zip(cont.keys, cont.values):
                if k is None:  # **spread: key set unknowable
                    return None
                if isinstance(k, ast.Constant) and k.value == idx:
                    return self.resolve_expr(csrc, v, cscope, _guard)
        return None

    def _literal_container(self, src, expr, scope_node, _depth=0):
        """Chase ``expr`` through Name bindings to a literal container node;
        returns (container, src, scope_node-for-its-free-names) or None."""
        if _depth > 8:
            return None
        if isinstance(expr, (ast.Tuple, ast.List, ast.Dict)):
            return expr, src, scope_node
        if not isinstance(expr, ast.Name):
            return None
        for node in self._scope_chain(src, scope_node):
            env = self._env(src, node)
            if expr.id in env:
                tag, val = env[expr.id]
                if tag != "expr":
                    return None
                return self._literal_container(src, val, node, _depth + 1)
        mi = self.symbols.by_path.get(src.path)
        got = self.symbols.resolve_member(mi, expr.id) if mi is not None else None
        if got is not None and got[0] == "assign":
            _, val, mi2 = got
            return self._literal_container(mi2.src, val, None, _depth + 1)
        return None

    def _resolve_name(self, src, name, scope_node, _guard):
        for node in self._scope_chain(src, scope_node):
            env = self._env(src, node)
            if name in env:
                return self._from_binding(src, env[name], node, _guard)
        mi = self.symbols.by_path.get(src.path)
        if mi is None:
            return None
        got = self.symbols.resolve_member(mi, name)
        return self._from_symbol(got, _guard)

    def _from_binding(self, src, binding, scope_node, _guard):
        tag, val = binding
        if tag == "def":
            return self._function_target(val)
        if tag == "instance":
            return Target("instance", cls=val)
        if tag == "expr":
            return self.resolve_expr(src, val, scope_node, _guard)
        return None  # opaque

    def _from_symbol(self, got, _guard):
        if got is None:
            return None
        tag = got[0]
        if tag == "func":
            return self._function_target(got[1])
        if tag == "class":
            return Target("class", cls=got[1])
        if tag == "module":
            return Target("module", mod=got[1])
        if tag == "assign":
            _, expr, mi = got
            return self.resolve_expr(mi.src, expr, None, _guard)
        return None

    def _member(self, base: Target, attr: str, _guard):
        if base.kind == "module":
            return self._from_symbol(self.symbols.resolve_member(base.mod, attr), _guard)
        if base.kind in ("instance", "class"):
            ci = base.cls
            if attr in ci.methods:
                t = self._function_target(ci.methods[attr])
                if t is not None and base.kind == "instance":
                    return dataclasses.replace(t, bound=True) if t.kind == "function" else t
                return t
            rhs = ci.attr_assigns.get(attr)
            if rhs is not None:
                # the RHS was written inside a method; its free names resolve
                # against the defining module's top-level scope
                return self.resolve_expr(ci.module.src, rhs, None, _guard)
        return None

    def _function_target(self, fi: FunctionInfo) -> Optional[Target]:
        if fi is None:
            return None
        t = Target("function", func=fi)
        # a def decorated with jax.jit / partial(jax.jit, ...) carries its
        # static/donate contract at every call site
        wrap = self._decorator_jit(fi)
        if wrap is not None:
            return dataclasses.replace(wrap, inner=t)
        return t

    def _decorator_jit(self, fi: FunctionInfo) -> Optional[Target]:
        aliases = fi.module.src.aliases
        for dec in fi.node.decorator_list:
            q = qualified_name(dec.func if isinstance(dec, ast.Call) else dec, aliases)
            if q in _JIT_WRAPPERS:
                return self._jit_target(dec if isinstance(dec, ast.Call) else None)
            if isinstance(dec, ast.Call) and q in _PARTIAL and dec.args:
                q2 = qualified_name(dec.args[0], aliases)
                if q2 in _JIT_WRAPPERS:
                    return self._jit_target(dec)
        return None

    def _jit_target(self, call: ast.Call | None, inner: Target | None = None) -> Target:
        donate: tuple[int, ...] = ()
        nums: tuple[int, ...] = ()
        names: tuple[str, ...] = ()
        for kw in call.keywords if call is not None else ():
            if kw.arg == "donate_argnums":
                donate = _int_tuple(kw.value) or ()
            elif kw.arg == "static_argnums":
                nums = _int_tuple(kw.value) or ()
            elif kw.arg == "static_argnames":
                names = _str_tuple(kw.value) or ()
        return Target("jit", inner=inner, donate=donate, static_nums=nums, static_names=names)

    def _resolve_call_result(self, src, call: ast.Call, scope_node, _guard):
        """What a call EVALUATES to (constructor -> instance, jit(...) -> a
        jit-wrapped callable, factory -> its summarized return)."""
        q = qualified_name(call.func, src.aliases)
        if q in _JIT_WRAPPERS:
            inner = self.resolve_expr(src, call.args[0], scope_node, _guard) if call.args else None
            return self._jit_target(call, inner)
        if q in _PARTIAL and call.args:
            q2 = qualified_name(call.args[0], src.aliases)
            if q2 in _JIT_WRAPPERS:
                return self._jit_target(call)
        callee = self.resolve_expr(src, call.func, scope_node, _guard)
        if callee is None:
            return None
        if callee.kind == "class":
            return Target("instance", cls=callee.cls)
        fi = callee.func if callee.kind == "function" else (
            callee.inner.func if callee.kind == "jit" and callee.inner is not None
            and callee.inner.kind == "function" else None
        )
        if fi is not None:
            self._summary_reads += 1
            summary = self.project.summaries.get(fi.qualname)
            if summary is not None and summary.returns is not None:
                return summary.returns
        return None

    # -- convenience for rules/tests ---------------------------------------

    def resolved_calls(self, src):
        """Every Call in ``src`` with its enclosing scope and resolution:
        list of (call_node, scope_node, Target-or-None)."""
        out = []
        for node in src.nodes:
            if isinstance(node, ast.Call):
                scope = self.enclosing_scope(src, node)
                out.append((node, scope, self.resolve_call(src, node, scope)))
        return out
