"""Concurrency model: thread roots, lock domains, and where shared state
lives — the interprocedural substrate for YAMT019/020/021
(rules_concurrency.py, docs/LINT.md "Concurrency rules").

Three cooperating pieces, all pure AST like the rest of the layer:

- **Thread roots.** Every ``threading.Thread(target=...)`` call in the
  project is a root: the target resolves through the call graph (plain
  names, ``self._method``, instances, nested defs — the shapes YAMT011
  parses file-locally, here resolved project-wide), and a ``lambda`` target
  roots every call its body makes. Each spawn SITE is its own region — two
  spawns of the same function are two regions — plus one synthetic ``main``
  region holding every entry-point function (a def no resolved in-package
  call site reaches: public API, HTTP handlers, module-level code).

- **Lock-domain summaries.** Per function, a linear walk tracks which locks
  are held (``with self._lock:`` / ``LOCK.acquire()``/``.release()``; locks
  are ``threading.Lock``/``RLock``/``Condition`` attributes or module
  globals, keyed by the ROOT class of an inheritance family so a base-class
  lock and a subclass use of it are the same token) around three kinds of
  event: ``self``-attribute reads/writes (mutating method calls like
  ``.append``/``.update`` count as writes), lock acquisitions, and
  known-blocking calls. Summaries propagate through resolved calls to
  fixpoint exactly like summaries.py: a caller holding ``A`` absorbs its
  callee's events with ``A`` added to their held-sets, so a blocking call
  three frames down still knows every lock above it. Events keep their own
  (path, line): findings land in the file containing the hazard.

- **Region attribution.** Events are attributed from the TOP of each region
  (the root target's summary / each main entry's summary), never from the
  middle — a helper that reads an attribute lock-free but is only ever
  called under a lock must inherit that lock, and only top-down propagation
  carries it. A function reached from two regions appears in both, with the
  held-sets each path actually provides.

Honest degradation, matching the framework's no-false-positive bar: opaque
call targets contribute nothing; a lock the model cannot name (aliased
through a local, stored in a container) simply is not tracked — every
widening is toward silence, not noise. Known blind spots are documented in
docs/LINT.md. ``__init__``/``__post_init__`` bodies are excluded from
attribute events (writes there happen-before any thread start), and
``threading.Event``/``queue.Queue``/``collections.deque``-typed attributes
are exempt shared state (their methods are the synchronization).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Optional

from .core import qualified_name
from .symbols import ClassInfo, FunctionInfo

MAIN_REGION = "main"

# lockable primitives the held-set tracks (Semaphores are resource counters
# with far-apart acquire/release pairs, not critical sections — excluded)
_LOCK_TYPES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}
# attribute types that ARE synchronization (or are internally synchronized):
# cross-thread access to them is the sanctioned mechanism, not a race
_SYNC_SAFE_TYPES = {
    *_LOCK_TYPES,
    "threading.Event",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "threading.Barrier",
    "threading.Thread",
    "threading.local",
    "queue.Queue",
    "queue.SimpleQueue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
    "collections.deque",
}
_QUEUE_TYPES = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue", "queue.SimpleQueue"}

# method calls on an attribute that mutate the container in place
_MUT_METHODS = {
    "append", "appendleft", "add", "extend", "insert", "remove", "discard",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "sort", "reverse",
}

# known-blocking calls by resolved qualified name
_BLOCKING_QUALS = {
    "time.sleep",
    "socket.create_connection",
    "urllib.request.urlopen",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "jax.block_until_ready",
    "jax.device_get",
}
# known-blocking method names on ANY receiver (strong signals; `.compile()`
# is the executable compile — `re.compile` is excluded by qualified name)
_BLOCKING_ATTRS = {"compile", "result", "getresponse", "recv", "accept", "sendall"}
_NOT_BLOCKING_QUALS = {"re.compile", "sre_compile.compile"}

_INIT_METHODS = {"__init__", "__post_init__", "__new__"}

_MAX_HELDSETS = 6  # per event site; extras are dropped (toward silence)
_MAX_ROUNDS = 12


def is_package_code(path: str) -> bool:
    """Same scope gate as YAMT007/011/012: a dir holding ``__init__.py``."""
    return os.path.exists(os.path.join(os.path.dirname(path), "__init__.py"))


@dataclasses.dataclass(frozen=True)
class ThreadRoot:
    """One ``threading.Thread(target=...)`` spawn site = one region."""

    region: str  # "path:line" — stable id
    target: FunctionInfo
    path: str
    line: int
    spawner_cls: Optional[str] = None  # qualname of the class spawning it
    spawner_family: Optional[str] = None  # family root of that class
    # (path, first line, last line) of the function containing the spawn:
    # its own accesses happen-before start() / after join(), not racily
    spawn_span: Optional[tuple] = None

    @property
    def label(self) -> str:
        return f"thread '{self.target.name}' (started at {os.path.basename(self.path)}:{self.line})"


class FnConc:
    """One function's lock-domain summary (own events + resolved callees').

    Each dict maps an event site to the set of possible held-lock frozensets
    observed on paths reaching it:

    - ``accesses``: (family, attr, kind 'r'|'w', path, line) -> held-sets
    - ``acquires``: (lock_token, path, line) -> held-sets at the acquire
    - ``blocking``: (description, path, line) -> held-sets
    """

    __slots__ = ("accesses", "acquires", "blocking")

    def __init__(self):
        self.accesses: dict[tuple, set[frozenset]] = {}
        self.acquires: dict[tuple, set[frozenset]] = {}
        self.blocking: dict[tuple, set[frozenset]] = {}

    def _add(self, table: dict, key: tuple, held: frozenset) -> None:
        hs = table.setdefault(key, set())
        if held not in hs and len(hs) < _MAX_HELDSETS:
            hs.add(held)

    def absorb(self, callee: "FnConc", held: frozenset) -> None:
        """Merge a callee's events, with the caller's held locks added."""
        for mine, theirs in (
            (self.accesses, callee.accesses),
            (self.acquires, callee.acquires),
            (self.blocking, callee.blocking),
        ):
            for key, heldsets in theirs.items():
                for h in heldsets:
                    self._add(mine, key, h | held)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, FnConc)
            and self.accesses == other.accesses
            and self.acquires == other.acquires
            and self.blocking == other.blocking
        )

    def copy(self) -> "FnConc":
        c = FnConc()
        c.accesses = {k: set(v) for k, v in self.accesses.items()}
        c.acquires = {k: set(v) for k, v in self.acquires.items()}
        c.blocking = {k: set(v) for k, v in self.blocking.items()}
        return c


@dataclasses.dataclass
class _ScanCtx:
    """Per-function facts needed while walking its body."""

    src: object
    mi: object
    fi: Optional[FunctionInfo]  # None for module-level pseudo-bodies
    self_name: Optional[str]
    family: Optional[str]  # family-root qualname when fi is a method


class ConcurrencyModel:
    """Built once per Project (``project.concurrency``); read by the rules."""

    def __init__(self, project):
        self.project = project
        self.symbols = project.symbols
        self.cg = project.callgraph
        project.summaries  # force the PRNG/returns fixpoint: sharper resolution

        self.family_root: dict[str, str] = {}  # class qualname -> root qualname
        self.family_attrs: dict[str, dict[str, Optional[ast.expr]]] = {}
        self._family_aliases: dict[str, dict] = {}  # root -> defining aliases
        self.lock_types: dict[str, str] = {}  # token -> Lock|RLock|Condition
        self.roots: list[ThreadRoot] = []
        self.regions: dict[str, Optional[ThreadRoot]] = {MAIN_REGION: None}
        self.summaries: dict[str, FnConc] = {}
        self.main_entries: list[str] = []

        self._locals: dict[str, FnConc] = {}
        self._calls: dict[str, list[tuple[str, frozenset]]] = {}
        self._called: set[str] = set()

        self._build_families()
        self._scan_all()
        self._find_thread_roots()
        self._fixpoint()
        self._pick_main_entries()

    # -- class families ------------------------------------------------------

    def _build_families(self) -> None:
        """Map every project class to the topmost project base of its
        inheritance chain, and merge ``attr_assigns`` across the family so a
        base-class lock/queue keeps one identity in every subclass."""
        classes: dict[str, ClassInfo] = {}
        for mi in self.symbols.modules.values():
            classes.update({ci.qualname: ci for ci in mi.classes.values()})

        parent: dict[str, str] = {}
        for ci in classes.values():
            for base in ci.node.bases:
                t = self.cg.resolve_expr(ci.module.src, base, None)
                if t is not None and t.kind == "class" and t.cls.qualname in classes:
                    parent[ci.qualname] = t.cls.qualname
                    break  # single-inheritance chains only; first project base wins
        for q in classes:
            root, seen = q, {q}
            while root in parent and parent[root] not in seen:
                root = parent[root]
                seen.add(root)
            self.family_root[q] = root

        for q, ci in classes.items():
            root = self.family_root[q]
            attrs = self.family_attrs.setdefault(root, {})
            self._family_aliases.setdefault(root, classes[root].module.src.aliases)
            for attr, rhs in ci.attr_assigns.items():
                if attr in attrs:
                    prev = attrs[attr]
                    if prev is None or rhs is None or ast.dump(prev) != ast.dump(rhs):
                        attrs[attr] = None  # family members disagree: opaque
                else:
                    attrs[attr] = rhs

        # lock tokens: sync-typed family attributes + module-level globals
        for root, attrs in self.family_attrs.items():
            aliases = self._family_aliases[root]
            for attr, rhs in attrs.items():
                kind = self._sync_kind(rhs, aliases)
                if kind in _LOCK_TYPES.values():
                    self.lock_types[f"{root}.{attr}"] = kind
        for mi in self.symbols.modules.values():
            for name, rhs in mi.assigns.items():
                kind = self._sync_kind(rhs, mi.src.aliases)
                if kind in _LOCK_TYPES.values():
                    self.lock_types[f"{mi.name}.{name}"] = kind

    @staticmethod
    def _sync_kind(rhs: Optional[ast.expr], aliases) -> Optional[str]:
        """'Lock'/'RLock'/'Condition', another _SYNC_SAFE_TYPES tail, or None."""
        if not isinstance(rhs, ast.Call):
            return None
        q = qualified_name(rhs.func, aliases)
        if q in _LOCK_TYPES:
            return _LOCK_TYPES[q]
        if q in _SYNC_SAFE_TYPES:
            return q.rsplit(".", 1)[-1]
        return None

    def attr_is_sync_safe(self, family: str, attr: str) -> bool:
        rhs = self.family_attrs.get(family, {}).get(attr)
        aliases = self._family_aliases.get(family, {})
        return self._sync_kind(rhs, aliases) is not None

    def attr_type_tail(self, family: str, attr: str) -> Optional[str]:
        rhs = self.family_attrs.get(family, {}).get(attr)
        if not isinstance(rhs, ast.Call):
            return None
        q = qualified_name(rhs.func, self._family_aliases.get(family, {}))
        return q if q else None

    # -- local scans ---------------------------------------------------------

    def _scan_all(self) -> None:
        for fi in self.symbols.by_node.values():
            ctx = self._ctx_for(fi)
            facts, calls = self._scan_body(ctx, fi.node.body)
            self._locals[fi.qualname] = facts
            self._calls[fi.qualname] = calls
        # module-level code is a main entry in its own right (singleton
        # construction, registration calls)
        for mi in self.symbols.modules.values():
            ctx = _ScanCtx(mi.src, mi, None, None, None)
            body = [
                st for st in mi.src.tree.body
                if not isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
            ]
            q = f"{mi.name}.<module>"
            facts, calls = self._scan_body(ctx, body)
            self._locals[q] = facts
            self._calls[q] = calls

    def _ctx_for(self, fi: FunctionInfo) -> _ScanCtx:
        self_name = None
        family = None
        if fi.cls is not None and fi.pos_params:
            self_name = fi.pos_params[0]
            family = self.family_root.get(fi.cls.qualname, fi.cls.qualname)
        return _ScanCtx(fi.module.src, fi.module, fi, self_name, family)

    def _scan_body(self, ctx: _ScanCtx, body: list) -> tuple[FnConc, list]:
        facts = FnConc()
        calls: list[tuple[str, frozenset]] = []
        self._walk_block(ctx, body, set(), facts, calls)
        return facts, calls

    def _walk_block(self, ctx, stmts, held: set, facts, calls) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # separate scopes, scanned on their own
            if isinstance(st, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in st.items:
                    self._scan_exprs(ctx, item.context_expr, held, facts, calls)
                    tok = self._lock_token(ctx, item.context_expr)
                    if tok is not None:
                        facts._add(facts.acquires, (tok, ctx.src.path, item.context_expr.lineno), frozenset(held))
                        acquired.append(tok)
                self._walk_block(ctx, st.body, held | set(acquired), facts, calls)
            elif isinstance(st, ast.If):
                self._scan_exprs(ctx, st.test, held, facts, calls)
                self._walk_block(ctx, st.body, set(held), facts, calls)
                self._walk_block(ctx, st.orelse, set(held), facts, calls)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._scan_exprs(ctx, st.iter, held, facts, calls)
                self._walk_block(ctx, st.body, set(held), facts, calls)
                self._walk_block(ctx, st.orelse, set(held), facts, calls)
            elif isinstance(st, ast.While):
                self._scan_exprs(ctx, st.test, held, facts, calls)
                self._walk_block(ctx, st.body, set(held), facts, calls)
            elif isinstance(st, ast.Try):
                # body/else/finally share the live held set so the
                # acquire-then-try/finally-release idiom tracks exactly;
                # handlers run with a snapshot
                self._walk_block(ctx, st.body, held, facts, calls)
                for h in st.handlers:
                    self._walk_block(ctx, h.body, set(held), facts, calls)
                self._walk_block(ctx, st.orelse, held, facts, calls)
                self._walk_block(ctx, st.finalbody, held, facts, calls)
            else:
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, ast.expr):
                        self._scan_exprs(ctx, child, held, facts, calls)
                # assignment/del targets are attribute WRITES
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        self._record_store(ctx, t, held, facts)
                elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
                    self._record_store(ctx, st.target, held, facts)
                elif isinstance(st, ast.Delete):
                    for t in st.targets:
                        self._record_store(ctx, t, held, facts)

    # -- expression scanning -------------------------------------------------

    def _scan_exprs(self, ctx, expr, held: set, facts, calls) -> None:
        """Scan one expression tree: calls (lock ops, blocking, callees) and
        self-attribute loads. Lambda bodies are deferred work — they run
        later, under whatever locks the call site then holds — so the walk
        prunes them rather than crediting them with the current held-set."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                self._scan_call(ctx, node, held, facts, calls)
            elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
                self._record_access(ctx, node, "r", held, facts)
            stack.extend(ast.iter_child_nodes(node))

    def _scan_call(self, ctx, call: ast.Call, held: set, facts, calls) -> None:
        src = ctx.src
        q = qualified_name(call.func, src.aliases)

        # lock method ops mutate the linear held-set
        if isinstance(call.func, ast.Attribute) and call.func.attr in ("acquire", "release"):
            tok = self._lock_token(ctx, call.func.value)
            if tok is not None:
                if call.func.attr == "acquire":
                    facts._add(facts.acquires, (tok, src.path, call.lineno), frozenset(held))
                    held.add(tok)
                else:
                    held.discard(tok)
                return

        blocking = self._blocking_desc(ctx, call, q)
        if blocking is not None:
            desc, released = blocking
            eff = frozenset(held - released)
            facts._add(facts.blocking, (desc, src.path, call.lineno), eff)

        # mutating method call on a self attribute = a write
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _MUT_METHODS
            and isinstance(call.func.value, ast.Attribute)
        ):
            self._record_access(ctx, call.func.value, "w", held, facts)

        # resolved callee edge for the fixpoint
        scope = ctx.fi.node if ctx.fi is not None else None
        target = self.cg.resolve_call(src, call, scope)
        if target is not None:
            fi = None
            if target.kind == "function":
                fi = target.func
            elif target.kind == "jit" and target.inner is not None and target.inner.kind == "function":
                fi = target.inner.func
            if fi is not None:
                calls.append((fi.qualname, frozenset(held)))

    def _blocking_desc(self, ctx, call: ast.Call, q) -> Optional[tuple[str, frozenset]]:
        """(description, locks-released-by-the-call) for a known-blocking
        call, else None. ``Condition.wait`` releases its own lock."""
        if q in _NOT_BLOCKING_QUALS:
            return None
        if q in _BLOCKING_QUALS:
            return (f"{q}(...)", frozenset())
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        recv = call.func.value
        if attr in _BLOCKING_ATTRS:
            return (f".{attr}()", frozenset())
        if attr == "wait":
            tok = self._lock_token(ctx, recv)
            if tok is not None:  # Condition.wait drops the condition's lock
                return (".wait()", frozenset({tok}))
            return (".wait()", frozenset())
        if attr == "join":
            # only a Thread-typed self attribute (str.join/os.path.join noise)
            fam_attr = self._self_attr(ctx, recv)
            if fam_attr is not None and self.attr_type_tail(*fam_attr) == "threading.Thread":
                return (".join()", frozenset())
            return None
        if attr == "get":
            fam_attr = self._self_attr(ctx, recv)
            if fam_attr is not None and self.attr_type_tail(*fam_attr) in _QUEUE_TYPES:
                if any(kw.arg == "block" and isinstance(kw.value, ast.Constant)
                       and kw.value.value is False for kw in call.keywords):
                    return None
                return ("queue .get()", frozenset())
        return None

    def _self_attr(self, ctx, expr) -> Optional[tuple[str, str]]:
        """(family, attr) when ``expr`` is ``self.<attr>`` in a method."""
        if (
            ctx.self_name is not None
            and isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == ctx.self_name
        ):
            return (ctx.family, expr.attr)
        return None

    def _lock_token(self, ctx, expr) -> Optional[str]:
        fam_attr = self._self_attr(ctx, expr)
        if fam_attr is not None:
            tok = f"{fam_attr[0]}.{fam_attr[1]}"
            return tok if tok in self.lock_types else None
        if isinstance(expr, ast.Name):
            tok = f"{ctx.mi.name}.{expr.id}"
            return tok if tok in self.lock_types else None
        return None

    def _record_store(self, ctx, target, held: set, facts) -> None:
        # self.x = ... / self.x[k] = ... / tuple targets
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._record_store(ctx, el, held, facts)
            return
        if isinstance(target, ast.Starred):
            self._record_store(ctx, target.value, held, facts)
            return
        if isinstance(target, ast.Subscript):
            target = target.value  # a subscript store mutates the container
        if isinstance(target, ast.Attribute):
            self._record_access(ctx, target, "w", held, facts)

    def _record_access(self, ctx, attr_node: ast.Attribute, kind: str, held: set, facts) -> None:
        fam_attr = self._self_attr(ctx, attr_node)
        if fam_attr is None:
            return
        if ctx.fi is not None and ctx.fi.name in _INIT_METHODS:
            return  # construction happens-before every thread start
        family, attr = fam_attr
        if f"{family}.{attr}" in self.lock_types or self.attr_is_sync_safe(family, attr):
            return  # the attribute IS the synchronization
        facts._add(
            facts.accesses, (family, attr, kind, ctx.src.path, attr_node.lineno), frozenset(held)
        )

    # -- thread roots --------------------------------------------------------

    def _find_thread_roots(self) -> None:
        for src in self.project.files:
            if src.tree is None:
                continue
            for node in src.nodes:
                if not isinstance(node, ast.Call):
                    continue
                if qualified_name(node.func, src.aliases) != "threading.Thread":
                    continue
                target = next((kw.value for kw in node.keywords if kw.arg == "target"), None)
                if target is None:
                    continue
                scope = self.cg.enclosing_scope(src, node)
                spawner = self.symbols.by_node.get(id(scope)) if scope is not None else None
                cls_q = spawner.cls.qualname if spawner is not None and spawner.cls is not None else None
                fam_q = self.family_root.get(cls_q) if cls_q is not None else None
                span = None
                if scope is not None:
                    span = (src.path, scope.lineno, getattr(scope, "end_lineno", scope.lineno))
                region = f"{src.path}:{node.lineno}"
                for fi in self._root_targets(src, target, scope):
                    root = ThreadRoot(region, fi, src.path, node.lineno, cls_q, fam_q, span)
                    self.roots.append(root)
                    self.regions[region] = root

    def _root_targets(self, src, target: ast.expr, scope) -> list[FunctionInfo]:
        """FunctionInfos a Thread target expression can enter: the resolved
        function, or — for a lambda — every resolved call in its body."""
        if isinstance(target, ast.Lambda):
            out = []
            for node in src.subtree(target.body):
                if isinstance(node, ast.Call):
                    t = self.cg.resolve_call(src, node, scope)
                    if t is not None and t.kind == "function":
                        out.append(t.func)
            return out
        t = self.cg.resolve_expr(src, target, scope)
        if t is not None and t.kind == "function":
            return [t.func]
        return []

    # -- fixpoint + attribution ----------------------------------------------

    def _fixpoint(self) -> None:
        self.summaries = {q: f.copy() for q, f in self._locals.items()}
        for _ in range(_MAX_ROUNDS):
            changed = False
            for q, calls in self._calls.items():
                s = self.summaries[q]
                before = s.copy()
                for callee_q, held in calls:
                    callee = self.summaries.get(callee_q)
                    if callee is not None and callee_q != q:
                        s.absorb(callee, held)
                changed |= s != before
            if not changed:
                break

    def _pick_main_entries(self) -> None:
        """Entry points of the synthetic ``main`` region: functions no
        resolved in-package call reaches (public API, handlers, callbacks)
        plus every module's top-level body. Thread targets and constructors
        are excluded — their events belong to their own region / to
        happens-before setup."""
        called = {callee for calls in self._calls.values() for callee, _ in calls}
        root_targets = {r.target.qualname for r in self.roots}
        for q in self._locals:
            if q.endswith(".<module>"):
                self.main_entries.append(q)
                continue
            if q in called or q in root_targets:
                continue
            name = q.rsplit(".", 1)[-1]
            if name in _INIT_METHODS:
                continue
            self.main_entries.append(q)

    # -- derived views for the rules ----------------------------------------

    def entry_summaries(self):
        """Yield (region_id, root_or_None, FnConc) for every region top."""
        for root in self.roots:
            s = self.summaries.get(root.target.qualname)
            if s is not None:
                yield root.region, root, s
        for q in self.main_entries:
            yield MAIN_REGION, None, self.summaries[q]

    def attr_events(self) -> dict[tuple[str, str], list]:
        """(family, attr) -> [(region, root, kind, path, line, heldsets)],
        attributed top-down from every region entry."""
        out: dict[tuple[str, str], list] = {}
        for region, root, s in self.entry_summaries():
            for (family, attr, kind, path, line), heldsets in s.accesses.items():
                out.setdefault((family, attr), []).append(
                    (region, root, kind, path, line, frozenset(heldsets))
                )
        return out

    def acquire_regions(self) -> dict[str, set[str]]:
        """lock token -> region ids whose code acquires it."""
        out: dict[str, set[str]] = {}
        for region, _root, s in self.entry_summaries():
            for (tok, _path, _line), _heldsets in s.acquires.items():
                out.setdefault(tok, set()).add(region)
        return out

    def lock_edges(self) -> tuple[dict[tuple[str, str], tuple[str, int]], dict[str, tuple[str, int]]]:
        """(ordered-edges, self-edges): ``A -> B`` when B is acquired while A
        is held (any function's summary — held-sets already carry caller
        context), with one witness site each. Self-edges only for
        non-reentrant Locks."""
        edges: dict[tuple[str, str], tuple[str, int]] = {}
        selfedges: dict[str, tuple[str, int]] = {}
        for s in self.summaries.values():
            for (tok, path, line), heldsets in s.acquires.items():
                for h in heldsets:
                    for a in h:
                        if a == tok:
                            if self.lock_types.get(tok) == "Lock":
                                selfedges.setdefault(tok, (path, line))
                        else:
                            edges.setdefault((a, tok), (path, line))
        return edges, selfedges

    def blocking_sites(self) -> dict[tuple[str, str, int], set[frozenset]]:
        """(desc, path, line) -> union of held-sets across every summary."""
        out: dict[tuple[str, str, int], set[frozenset]] = {}
        for s in self.summaries.values():
            for key, heldsets in s.blocking.items():
                out.setdefault(key, set()).update(heldsets)
        return out


def short_lock(token: str) -> str:
    """'pkg.mod.Class._lock' -> 'Class._lock' for messages."""
    parts = token.rsplit(".", 2)
    return ".".join(parts[-2:]) if len(parts) >= 2 else token
