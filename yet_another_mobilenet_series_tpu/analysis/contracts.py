"""Cross-process wire-contract extraction (YAMT022-025's ground truth).

The fleet's correctness lives partly in STRING contracts that cross process
boundaries: typed exceptions mapped to wire verdicts in ``_ERROR_MAP``,
custom headers sent by one tier and parsed by another, registry metric
names that must appear in the docs taxonomy and ``PROM_LABEL_FAMILIES``,
and config dataclass sections that must be registered in
``_SECTION_TYPES``. One :class:`ContractModel` per Project extracts all
four surfaces in a single pass over the package ASTs (plus the
``docs/OBSERVABILITY.md`` taxonomy found by walking up from the package),
so the rules in rules_contracts.py are pure set comparisons.

Extraction is literal-only, matching the framework's no-guess bar: a header
name built at runtime, a metric name passed through a variable (unless it
chases to a module-level string constant), an ``_ERROR_MAP`` row holding a
computed class — all degrade to absence, and every rule treats absence as
silence, not a finding.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Optional

from .concurrency import is_package_code
from .core import qualified_name

# custom wire headers: the X- namespace plus Retry-After (RFC 9110's
# backpressure hint, which the router parses as its ejection discriminator).
# Standard entity headers (Content-Type/Length, Host...) are out of scope.
_HEADER_RE = re.compile(r"^(X-[A-Za-z0-9-]+|Retry-After)$")

_SEND_METHODS = {"send_header", "putheader", "add_header"}
_PARSE_METHODS = {"get", "getheader"}
_METRIC_METHODS = {"counter", "gauge", "histogram"}

# backticked dotted tokens in the observability doc; segments carrying
# placeholder syntax (`<class>`, `{short,long}`, `d<i>`) mark family forms
_DOC_TOKEN_RE = re.compile(r"`((?:[A-Za-z_][\w]*|)(?:\.[\w<>{},]+)+)`")
_PLAIN_SEG_RE = re.compile(r"^[a-z0-9_]+$")

_DOC_RELPATH = os.path.join("docs", "OBSERVABILITY.md")
_MAX_WALK_UP = 10


@dataclasses.dataclass(frozen=True)
class Site:
    path: str
    line: int


@dataclasses.dataclass
class ErrorMap:
    """One module-level ``_ERROR_MAP`` list: the typed-exception -> wire
    verdict table, plus the classes the same module handles by hand
    (``isinstance`` dispatch, narrow ``except`` clauses)."""

    path: str
    line: int
    mapped: list[str]  # class keys, row order
    tags: list[str]
    handled: set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class ConfigSchema:
    """One module holding ``_SECTION_TYPES``: its dataclasses, which fields
    nest another dataclass (sections), and the registration dict."""

    path: str
    registered: set[str]
    registry_line: int
    # (owner class, field name, annotation class name, line) for fields
    # whose annotation names a sibling dataclass
    section_fields: list[tuple[str, str, str, int]]
    # (owner class, field name, line) for every plain field
    plain_fields: list[tuple[str, str, int]]


class ContractModel:
    """All four contract surfaces of one Project, extracted once."""

    def __init__(self, project):
        self.project = project
        self.headers_sent: dict[str, list[Site]] = {}
        self.headers_parsed: dict[str, list[Site]] = {}
        self.error_map: Optional[ErrorMap] = None
        self.metric_literals: dict[str, list[Site]] = {}  # full literal names
        self.metric_families: dict[str, list[Site]] = {}  # f-string prefixes
        self.prom_families: Optional[set[str]] = None
        self.prom_families_site: Optional[Site] = None
        self.config: Optional[ConfigSchema] = None
        self.attr_reads: set[str] = set()  # attr names read outside config
        self._doc_cache: dict[str, Optional[str]] = {}
        self._doc_names: dict[str, set[str]] = {}
        self._extract()

    # -- doc taxonomy -------------------------------------------------------

    def doc_for(self, path: str) -> Optional[str]:
        """The ``docs/OBSERVABILITY.md`` governing ``path``, found by walking
        up from its directory (nearest wins, so fixture trees carry their
        own taxonomy); None when there is none to check against."""
        d = os.path.dirname(os.path.abspath(path))
        chain = []
        for _ in range(_MAX_WALK_UP):
            if d in self._doc_cache:
                found = self._doc_cache[d]
                break
            chain.append(d)
            cand = os.path.join(d, _DOC_RELPATH)
            if os.path.isfile(cand):
                found = cand
                break
            parent = os.path.dirname(d)
            if parent == d:
                found = None
                break
            d = parent
        else:
            found = None
        for c in chain:
            self._doc_cache[c] = found
        return found

    def doc_names(self, doc_path: str) -> set[str]:
        """Normalized dotted names documented in the taxonomy: each
        backticked token keeps its leading plain segments (placeholder
        segments like ``<class>`` mark the name as a labeled family —
        the truncated prefix is what code-side names are matched against).

        The taxonomy elides siblings — ``serve.netchaos.connections`` /
        ``.blackholed`` / ``.resets`` — and appended suffixes —
        ``serve.shed_deadline (+ `.<class>`)``. A token starting with ``.``
        expands against the most recent full name on the same line, both as
        a sibling (last segment replaced) and as an extension (appended);
        the union over-approximates, which only ever WIDENS the documented
        set — safe for a coverage check."""
        got = self._doc_names.get(doc_path)
        if got is not None:
            return got
        names: set[str] = set()
        try:
            with open(doc_path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            text = ""
        for line in text.splitlines():
            base = None  # last full dotted name seen on this line
            for m in _DOC_TOKEN_RE.finditer(line):
                tok = m.group(1)
                if tok.startswith("."):
                    if base is None:
                        continue
                    expansions = [base + tok]
                    parent = base.rsplit(".", 1)[0]
                    if "." in base:
                        expansions.append(parent + tok)
                else:
                    expansions = [tok]
                for full in expansions:
                    segs = []
                    for seg in full.split("."):
                        if not _PLAIN_SEG_RE.match(seg):
                            break
                        segs.append(seg)
                    if len(segs) >= 2:
                        names.add(".".join(segs))
                if not tok.startswith("."):
                    base = tok
        self._doc_names[doc_path] = names
        return names

    def documented(self, name: str, doc_path: str) -> bool:
        """A code-side metric name (or family prefix) is documented when the
        taxonomy carries it, any dotted prefix of it (a doc row naming the
        family covers every per-label sample), or an extension of it (a doc
        row enumerating samples covers the family)."""
        names = self.doc_names(doc_path)
        if name in names:
            return True
        parts = name.split(".")
        for i in range(2, len(parts)):
            if ".".join(parts[:i]) in names:
                return True
        prefix = name + "."
        return any(n.startswith(prefix) for n in names)

    # -- extraction ---------------------------------------------------------

    def _extract(self) -> None:
        cfg_candidates: list = []
        # one pass over every file's node cache: contract literals come from
        # package code, attr reads from everywhere (the config module's own
        # reads are dropped once it is known — after the loop)
        per_file_attrs: dict[str, set[str]] = {}
        for src in self.project.files:
            if src.tree is None:
                continue
            per_file_attrs[src.path] = self._scan_file(src, is_package_code(src.path))
        for src in self.project.files:
            if src.tree is None or not is_package_code(src.path):
                continue
            for st in src.tree.body:
                if (isinstance(st, ast.Assign) and len(st.targets) == 1
                        and isinstance(st.targets[0], ast.Name)):
                    tname, value = st.targets[0].id, st.value
                elif (isinstance(st, ast.AnnAssign) and isinstance(st.target, ast.Name)
                        and st.value is not None):
                    tname, value = st.target.id, st.value
                else:
                    continue
                if tname == "_ERROR_MAP" and self.error_map is None:
                    self.error_map = self._read_error_map(src, value, st.lineno)
                elif tname == "PROM_LABEL_FAMILIES" and isinstance(value, ast.Dict):
                    self.prom_families = {
                        k.value for k in value.keys
                        if isinstance(k, ast.Constant) and isinstance(k.value, str)
                    }
                    self.prom_families_site = Site(src.path, st.lineno)
                elif tname == "_SECTION_TYPES" and isinstance(value, ast.Dict):
                    cfg_candidates.append((src, value, st.lineno))
        if self.error_map is not None:
            self._read_handled(self.error_map)
        if cfg_candidates:
            self.config = self._read_config(*cfg_candidates[0])
            cfg_path = self.config.path
        else:
            cfg_path = None
        for path, attrs in per_file_attrs.items():
            if path != cfg_path:
                self.attr_reads |= attrs

    def _scan_file(self, src, pkg: bool) -> set[str]:
        """One walk of ``src``'s node cache: records this file's contract
        literals (package code only) and returns its attribute-read names."""
        attrs: set[str] = set()
        for node in src.nodes:
            if isinstance(node, ast.Attribute):
                attrs.add(node.attr)
                continue
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Name)
                    and f.id in ("getattr", "hasattr")
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)
                ):
                    attrs.add(node.args[1].value)
                elif pkg and isinstance(f, ast.Attribute):
                    attr = f.attr
                    arg0 = node.args[0] if node.args else None
                    if attr in _SEND_METHODS or attr in _PARSE_METHODS:
                        if isinstance(arg0, ast.Constant) and isinstance(arg0.value, str) \
                                and _HEADER_RE.match(arg0.value):
                            book = (self.headers_sent if attr in _SEND_METHODS
                                    else self.headers_parsed)
                            self._hit(book, arg0.value, src, node)
                    if attr in _METRIC_METHODS and arg0 is not None:
                        self._metric_arg(src, arg0)
                continue
            if not pkg:
                continue
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                            and _HEADER_RE.match(k.value):
                        self._hit(self.headers_sent, k.value, src, k)
            elif isinstance(node, ast.Subscript):
                if (
                    isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and _HEADER_RE.match(node.slice.value)
                ):
                    book = (self.headers_sent if isinstance(node.ctx, (ast.Store, ast.Del))
                            else self.headers_parsed)
                    self._hit(book, node.slice.value, src, node)
        return attrs

    @staticmethod
    def _hit(book: dict[str, list[Site]], name: str, src, node) -> None:
        book.setdefault(name, []).append(Site(src.path, node.lineno))

    # -- metrics ------------------------------------------------------------

    def _metric_arg(self, src, arg: ast.expr) -> None:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if "." in arg.value:
                self._hit(self.metric_literals, arg.value, src, arg)
            return
        if not isinstance(arg, ast.JoinedStr):
            return  # a plain variable: opaque, contributes nothing
        prefix = ""
        for part in arg.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                prefix += part.value
                continue
            if isinstance(part, ast.FormattedValue) and isinstance(part.value, ast.Name):
                const = self._module_str_const(src, part.value.id)
                if const is not None:
                    prefix += const
                    continue
            break  # first unresolvable substitution ends the literal prefix
        # a family is a dotted prefix ending at a label substitution:
        # f"serve.bucket_hits.{b}" -> "serve.bucket_hits". A one-segment
        # prefix (f"device.{name}...") is opaque — never a guess.
        if prefix.endswith(".") and "." in prefix[:-1]:
            self._hit(self.metric_families, prefix[:-1], src, arg)

    def _module_str_const(self, src, name: str) -> Optional[str]:
        """Chase a bare name to a module-level string constant (possibly
        imported from a sibling module): ``f"{ROUTER_LATENCY}.{cls}"``."""
        mi = self.project.symbols.by_path.get(src.path)
        for _ in range(4):
            if mi is None:
                return None
            got = self.project.symbols.resolve_member(mi, name)
            if got is None or got[0] != "assign":
                return None
            _, expr, mi2 = got
            if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
                return expr.value
            if isinstance(expr, ast.Name):
                mi, name = mi2, expr.id
                continue
            return None
        return None

    # -- error map ----------------------------------------------------------

    def _read_error_map(self, src, value: ast.expr, lineno: int) -> Optional[ErrorMap]:
        if not isinstance(value, (ast.List, ast.Tuple)):
            return None
        mapped: list[str] = []
        tags: list[str] = []
        for row in value.elts:
            if not isinstance(row, (ast.Tuple, ast.List)) or len(row.elts) < 3:
                continue
            key = self._class_key(src, row.elts[0])
            tag = row.elts[2]
            if key is not None:
                mapped.append(key)
            if isinstance(tag, ast.Constant) and isinstance(tag.value, str):
                tags.append(tag.value)
        return ErrorMap(src.path, lineno, mapped, tags)

    def _read_handled(self, em: ErrorMap) -> None:
        src = next((s for s in self.project.files if s.path == em.path), None)
        if src is None:
            return
        for node in src.nodes:
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "isinstance"
                and len(node.args) == 2
            ):
                elts = (node.args[1].elts if isinstance(node.args[1], ast.Tuple)
                        else [node.args[1]])
                for e in elts:
                    key = self._class_key(src, e)
                    # exception classes are CamelCase: a lowercase external
                    # "name" is a loop variable over the map, not a class
                    if key is not None and key.rsplit(".", 1)[-1][:1].isupper():
                        em.handled.add(key)
            elif isinstance(node, ast.ExceptHandler) and node.type is not None:
                elts = (node.type.elts if isinstance(node.type, ast.Tuple)
                        else [node.type])
                for e in elts:
                    key = self._class_key(src, e)
                    if key is not None and key.rsplit(".", 1)[-1] not in (
                            "Exception", "BaseException"):
                        em.handled.add(key)

    def _class_key(self, src, expr: ast.expr) -> Optional[str]:
        cg = self.project.callgraph
        t = cg.resolve_expr(src, expr, cg.enclosing_scope(src, expr))
        if t is not None and t.kind == "class":
            return t.cls.qualname
        return qualified_name(expr, src.aliases)

    # -- config schema ------------------------------------------------------

    def _read_config(self, src, value: ast.Dict, lineno: int) -> ConfigSchema:
        registered = {
            k.value for k in value.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
        local_classes = {
            n.name for n in src.tree.body
            if isinstance(n, ast.ClassDef) and self._is_dataclass(src, n)
        }
        section_fields: list[tuple[str, str, str, int]] = []
        plain_fields: list[tuple[str, str, int]] = []
        for n in src.tree.body:
            if not (isinstance(n, ast.ClassDef) and n.name in local_classes):
                continue
            for f in n.body:
                if not (isinstance(f, ast.AnnAssign) and isinstance(f.target, ast.Name)):
                    continue
                ann = f.annotation
                ann_name = ann.id if isinstance(ann, ast.Name) else (
                    ann.value if isinstance(ann, ast.Constant)
                    and isinstance(ann.value, str) else None
                )
                if ann_name in local_classes:
                    section_fields.append((n.name, f.target.id, ann_name, f.lineno))
                else:
                    plain_fields.append((n.name, f.target.id, f.lineno))
        return ConfigSchema(src.path, registered, lineno, section_fields, plain_fields)

    @staticmethod
    def _is_dataclass(src, node: ast.ClassDef) -> bool:
        for dec in node.decorator_list:
            q = qualified_name(dec.func if isinstance(dec, ast.Call) else dec, src.aliases)
            if q and q.rsplit(".", 1)[-1] == "dataclass":
                return True
        return False
