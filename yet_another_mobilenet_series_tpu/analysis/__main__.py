"""`python -m yet_another_mobilenet_series_tpu.analysis` -> yamt-lint."""

import sys

from .cli import main

sys.exit(main())
