"""Project-wide symbol table: the name-resolution half of the
interprocedural layer (docs/LINT.md "Architecture").

One :class:`ModuleInfo` per parsed source file, holding its top-level
functions, classes (with methods and ``self.x = ...`` attribute assignments),
and simple top-level name bindings. Module names are derived from the on-disk
package structure (a directory chain of ``__init__.py``), so
``yet_another_mobilenet_series_tpu/train/steps.py`` resolves as
``yet_another_mobilenet_series_tpu.train.steps`` and a bare fixture file as
its stem. Imports are recorded structurally (module, member, relative level)
rather than as flattened dotted strings, because ``from . import core``
and ``from .core import f`` need different resolution arithmetic.

Everything here is pure AST bookkeeping — resolution logic that needs local
dataflow (instances, jit wrappers, returned closures) lives in
``callgraph.py``; per-function PRNG/donation facts live in ``summaries.py``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Optional


@dataclasses.dataclass
class ImportEntry:
    """One imported binding: ``bound`` resolves to ``member`` of ``module``
    (``member=None`` for whole-module imports), ``level`` counting the
    leading dots of a relative import."""

    bound: str
    module: str
    member: Optional[str]
    level: int


@dataclasses.dataclass
class FunctionInfo:
    """A def anywhere in a module (top-level, method, or nested closure)."""

    qualname: str  # "module.fn", "module.Class.method", "module.outer.inner"
    module: "ModuleInfo"
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls: Optional["ClassInfo"] = None
    parent: Optional["FunctionInfo"] = None  # enclosing def for closures

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def pos_params(self) -> list[str]:
        a = self.node.args
        return [x.arg for x in (*a.posonlyargs, *a.args)]

    @property
    def kwonly_params(self) -> list[str]:
        return [x.arg for x in self.node.args.kwonlyargs]

    @property
    def all_params(self) -> set[str]:
        a = self.node.args
        return set(self.pos_params) | set(self.kwonly_params) | {
            x.arg for x in (a.vararg, a.kwarg) if x is not None
        }


@dataclasses.dataclass
class ClassInfo:
    qualname: str
    module: "ModuleInfo"
    node: ast.ClassDef
    methods: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    # attribute name -> RHS expression of a single consistent `self.x = ...`
    # (or class-level `x = ...`); conflicting assignments drop the attr to
    # opaque (absent) rather than guessing
    attr_assigns: dict[str, Optional[ast.expr]] = dataclasses.field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.node.name


@dataclasses.dataclass
class ModuleInfo:
    name: str  # dotted
    src: object  # SourceFile (core.py; untyped to avoid the import cycle)
    functions: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    # top-level single-target Name assigns; None marks a conflicted binding
    assigns: dict[str, Optional[ast.expr]] = dataclasses.field(default_factory=dict)
    imports: dict[str, ImportEntry] = dataclasses.field(default_factory=dict)


def module_name_for(path: str) -> str:
    """Dotted module name from the on-disk package chain of ``path``."""
    path = os.path.abspath(path)
    base = os.path.basename(path)
    stem = base[:-3] if base.endswith(".py") else base
    parts = [] if stem == "__init__" else [stem]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(reversed(parts)) or stem


def _record_attr_assign(ci: ClassInfo, attr: str, value: ast.expr) -> None:
    if attr in ci.attr_assigns:
        prev = ci.attr_assigns[attr]
        if prev is None or ast.dump(prev) != ast.dump(value):
            ci.attr_assigns[attr] = None  # conflicting writes: opaque
    else:
        ci.attr_assigns[attr] = value


class SymbolTable:
    """Modules by dotted name, every FunctionInfo by AST node id, and the
    import-resolution arithmetic shared by the call graph."""

    def __init__(self, project):
        self.modules: dict[str, ModuleInfo] = {}
        self.by_path: dict[str, ModuleInfo] = {}
        self.by_node: dict[int, FunctionInfo] = {}
        self._ambiguous: set[str] = set()
        for src in project.files:
            if src.tree is None:
                continue
            mi = self._index_module(src)
            if mi.name in self.modules:
                self._ambiguous.add(mi.name)
            else:
                self.modules[mi.name] = mi
            self.by_path[src.path] = mi

    # -- indexing -----------------------------------------------------------

    def _index_module(self, src) -> ModuleInfo:
        mi = ModuleInfo(module_name_for(src.path), src)
        for node in src.nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        mi.imports[a.asname] = ImportEntry(a.asname, a.name, None, 0)
                    else:
                        top = a.name.split(".")[0]
                        mi.imports[top] = ImportEntry(top, top, None, 0)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "*":
                        continue
                    bound = a.asname or a.name
                    mi.imports[bound] = ImportEntry(bound, node.module or "", a.name, node.level)
        for st in src.tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi.functions[st.name] = self._index_function(mi, st, f"{mi.name}.{st.name}", None, None)
            elif isinstance(st, ast.ClassDef):
                mi.classes[st.name] = self._index_class(mi, st)
            elif (
                isinstance(st, ast.Assign)
                and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
            ):
                name = st.targets[0].id
                if name in mi.assigns:
                    mi.assigns[name] = None  # rebound at top level: opaque
                else:
                    mi.assigns[name] = st.value
        return mi

    def _index_function(self, mi, node, qualname, cls, parent) -> FunctionInfo:
        fi = FunctionInfo(qualname, mi, node, cls, parent)
        self.by_node[id(node)] = fi
        for st in mi.src.subtree(node):
            if st is not node and isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(st) not in self.by_node:
                    # nearest registered ancestor wins as parent; qualname
                    # nests for uniqueness within the module
                    self._index_function(mi, st, f"{qualname}.{st.name}", cls, fi)
        return fi

    def _index_class(self, mi, node: ast.ClassDef) -> ClassInfo:
        ci = ClassInfo(f"{mi.name}.{node.name}", mi, node)
        for st in node.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[st.name] = self._index_function(
                    mi, st, f"{ci.qualname}.{st.name}", ci, None
                )
            elif (
                isinstance(st, ast.Assign)
                and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
            ):
                _record_attr_assign(ci, st.targets[0].id, st.value)
        # `self.x = ...` in any method body
        for m in ci.methods.values():
            for st in mi.src.subtree(m.node):
                if not (isinstance(st, ast.Assign) and len(st.targets) == 1):
                    continue
                t = st.targets[0]
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    _record_attr_assign(ci, t.attr, st.value)
        return ci

    # -- resolution ---------------------------------------------------------

    def resolve_module(self, from_mod: ModuleInfo, dotted: str, level: int = 0) -> Optional[ModuleInfo]:
        """The ModuleInfo a (possibly relative) import path refers to, or
        None. Absolute paths match exactly first, then by unambiguous dotted
        suffix (fixture files import each other as bare top-level names)."""
        if level > 0:
            pkg = from_mod.name.split(".")[:-1]  # the module's own package
            if level - 1 > len(pkg):
                return None
            base = pkg[: len(pkg) - (level - 1)]
            full = ".".join(base + ([dotted] if dotted else []))
            mi = self.modules.get(full)
            return None if mi is None or full in self._ambiguous else mi
        if dotted in self.modules:
            return None if dotted in self._ambiguous else self.modules[dotted]
        tail = "." + dotted
        hits = [m for name, m in self.modules.items() if name.endswith(tail)]
        return hits[0] if len(hits) == 1 else None

    def resolve_member(self, mi: ModuleInfo, name: str):
        """('func', fi) | ('class', ci) | ('assign', expr, mi) |
        ('module', sub) | None for a member of module ``mi``."""
        if name in mi.functions:
            return ("func", mi.functions[name])
        if name in mi.classes:
            return ("class", mi.classes[name])
        if mi.assigns.get(name) is not None:
            return ("assign", mi.assigns[name], mi)
        sub = self.modules.get(f"{mi.name}.{name}")
        if sub is not None:
            return ("module", sub)
        # member re-exported through the module's own imports
        ent = mi.imports.get(name)
        if ent is not None:
            return self.resolve_import(mi, ent)
        return None

    def resolve_import(self, from_mod: ModuleInfo, ent: ImportEntry):
        """What an ImportEntry binds: same tagged-union shape as
        :meth:`resolve_member`, or None for anything outside the project."""
        target_mod = self.resolve_module(from_mod, ent.module, ent.level)
        if ent.member is None:
            return None if target_mod is None else ("module", target_mod)
        if target_mod is not None:
            got = self.resolve_member(target_mod, ent.member)
            if got is not None:
                return got
        # `from pkg import mod` where pkg/__init__ isn't in the linted set
        # still resolves when pkg.mod is
        dotted = f"{ent.module}.{ent.member}" if ent.module else ent.member
        as_mod = self.resolve_module(from_mod, dotted, ent.level)
        return None if as_mod is None else ("module", as_mod)
