"""YAMT015 — subprocess spawns without a bounded cleanup path.

A supervisor that spawns a child and then dies on the exception edge leaks
that child: the fleet supervisor (cli/fleet.py) spawning N serving replicas
is the motivating shape — a replica that outlives its supervisor keeps its
port, its memory, and (on a TPU host) the device lease, and nothing will
ever reap it. The complementary hazard is the UNBOUNDED blocking wait:
``subprocess.run``/``check_output`` with no ``timeout=`` turns a wedged
child into a wedged parent — the exact failure the serving stack's drain
timeouts exist to prevent.

Two checks, package code only (a directory holding ``__init__.py`` —
standalone scripts and tests exempt, like YAMT007/YAMT011):

1. **``subprocess.Popen(...)``** — the spawning code must own a bounded
   cleanup path. Sanctioned shapes:

   - the enclosing function contains an exception-edge cleanup: a
     ``.terminate()`` / ``.kill()`` / ``.send_signal()`` / bounded
     ``.wait(timeout=...)`` call inside an ``except`` handler or ``finally``
     body (calling a cleanup METHOD named ``kill``/``terminate`` counts —
     the wrapper-method idiom);
   - the handle is assigned to ``self.<attr>`` and some function in the
     file cleans that attribute up (``self._proc.terminate()`` in a
     ``stop()`` method — ownership handed to an object that can reap it).

   A bare ``.wait()`` with no timeout is NOT cleanup — it is the unbounded
   hang the rule exists to prevent.

2. **``subprocess.run`` / ``call`` / ``check_call`` / ``check_output``**
   without a ``timeout=`` keyword — an unbounded wait on the child.

Resolution stays file-local and silence-biased like the sibling rules:
handles that escape to other modules, factory results, and dynamically
built commands degrade to silence, not noise.
"""

from __future__ import annotations

import ast
import os

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

_WAIT_FUNCS = ("subprocess.run", "subprocess.call", "subprocess.check_call",
               "subprocess.check_output")
_CLEANUP_ATTRS = {"terminate", "kill", "send_signal"}


def _is_cleanup_call(node: ast.AST) -> ast.expr | None:
    """The receiver expression when ``node`` is a bounded cleanup call
    (terminate/kill/send_signal, or wait WITH a timeout), else None."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
        return None
    attr = node.func.attr
    if attr in _CLEANUP_ATTRS:
        return node.func.value
    if attr == "wait" and (node.args or any(kw.arg == "timeout" for kw in node.keywords)):
        return node.func.value
    return None


def _self_attr(node: ast.expr) -> str | None:
    """'attr' when ``node`` is exactly ``self.<attr>``, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _Index(ast.NodeVisitor):
    """One pass over the module: Popen/run call sites with their enclosing
    function, functions owning an exception-edge cleanup, and the set of
    ``self.<attr>`` names cleaned up anywhere in the file."""

    def __init__(self, aliases: dict[str, str]):
        self._aliases = aliases
        self._fn_stack: list[ast.AST] = []
        self.popen_sites: list[tuple[ast.Call, ast.AST | None, str | None]] = []
        self.wait_sites: list[tuple[ast.Call, str]] = []
        self.edge_cleanup_fns: set[int] = set()  # id() of functions with one
        self.cleaned_self_attrs: set[str] = set()

    def _visit_fn(self, node):
        self._fn_stack.append(node)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _visit_fn

    def visit_Try(self, node: ast.Try) -> None:
        edge = list(node.handlers) + list(node.finalbody)
        for part in edge:
            for sub in ast.walk(part):
                if _is_cleanup_call(sub) is not None and self._fn_stack:
                    self.edge_cleanup_fns.add(id(self._fn_stack[-1]))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # self.<attr> = subprocess.Popen(...): ownership lands on the object
        if (isinstance(node.value, ast.Call)
                and qualified_name(node.value.func, self._aliases) == "subprocess.Popen"
                and len(node.targets) == 1):
            attr = _self_attr(node.targets[0])
            if attr is not None:
                fn = self._fn_stack[-1] if self._fn_stack else None
                self.popen_sites.append((node.value, fn, attr))
                self.generic_visit(node)
                return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        recv = _is_cleanup_call(node)
        if recv is not None:
            attr = _self_attr(recv)
            if attr is not None:
                self.cleaned_self_attrs.add(attr)
        q = qualified_name(node.func, self._aliases)
        if q == "subprocess.Popen":
            if not any(site[0] is node for site in self.popen_sites):
                fn = self._fn_stack[-1] if self._fn_stack else None
                self.popen_sites.append((node, fn, None))
        elif q in _WAIT_FUNCS:
            if not any(kw.arg == "timeout" for kw in node.keywords):
                self.wait_sites.append((node, q))
        self.generic_visit(node)


@register
class UnboundedSubprocess(Rule):
    id = "YAMT015"
    name = "unbounded-subprocess"
    description = (
        "package code spawning a subprocess without a bounded wait/terminate path "
        "on the exception edge (a leaked child outlives its supervisor), or blocking "
        "on subprocess.run/check_* with no timeout (a wedged child wedges the parent)"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        # package code only: a dir with __init__.py (scripts/tests exempt)
        if not os.path.exists(os.path.join(os.path.dirname(src.path), "__init__.py")):
            return []
        if "subprocess" not in src.text:
            return []
        index = _Index(src.aliases)
        index.visit(src.tree)
        findings: list[Finding] = []
        for call, fn, self_attr in index.popen_sites:
            if fn is not None and id(fn) in index.edge_cleanup_fns:
                continue  # the spawner itself guards the exception edge
            if self_attr is not None and self_attr in index.cleaned_self_attrs:
                continue  # ownership handed to an object that can reap it
            where = f"in '{fn.name}'" if fn is not None else "at module level"
            findings.append(Finding(
                src.path, call.lineno, call.col_offset, self.id,
                f"subprocess.Popen {where} has no bounded cleanup path: add a "
                "terminate/kill/wait(timeout=...) on the exception edge (except/"
                "finally), or store the handle on an object whose stop path reaps it",
            ))
        for call, q in index.wait_sites:
            findings.append(Finding(
                src.path, call.lineno, call.col_offset, self.id,
                f"{q} without timeout= blocks the parent unboundedly on a wedged "
                "child: pass an explicit timeout",
            ))
        return findings
