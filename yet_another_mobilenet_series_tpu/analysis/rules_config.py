"""YAMT005 — config-key drift between apps/*.yml and config.py.

config.py's strict ``_build`` rejects unknown keys — but only when the yml is
actually LOADED, i.e. a typo in an experiment file costs a failed cluster
launch (or worse, sits in an app nobody has run since the schema changed).
This rule replays the same strict check statically: every key in every
``.yml`` under the linted tree must name a field of the Config schema parsed
out of the project's ``config.py`` (sections one level deep, matching
``_build``'s dataclass dispatch). ``_base_`` is the inheritance key and is
exempt.
"""

from __future__ import annotations

import ast
import os
import re

from .core import Finding, Project, Rule, register
from .rules_spmd import _class_fields, _is_dataclass


def _config_schema(project: Project):
    """Parse the project's config.py into {'': {top field: section name|None},
    section name: [field, ...]}. None when the project has no config.py with
    a Config dataclass."""
    for src in project.files:
        if os.path.basename(src.path) != "config.py":
            continue
        dataclasses = {
            node.name: node
            for node in src.nodes
            if isinstance(node, ast.ClassDef) and _is_dataclass(node, src.aliases)
        }
        root = dataclasses.get("Config")
        if root is None:
            continue
        sections: dict[str, list[str]] = {
            name: _class_fields(node) for name, node in dataclasses.items()
        }
        top: dict[str, str | None] = {}
        for st in root.body:
            if isinstance(st, ast.AnnAssign) and isinstance(st.target, ast.Name):
                ann = st.annotation
                ann_name = ann.id if isinstance(ann, ast.Name) else (
                    ann.value if isinstance(ann, ast.Constant) and isinstance(ann.value, str) else None
                )
                top[st.target.id] = ann_name if ann_name in sections else None
        return top, sections
    return None


def _key_line(lines: list[str], key: str, start: int = 0, stop: int | None = None, indented: bool = False) -> int:
    """1-based line of the first `key:` occurrence in [start, stop)."""
    pat = re.compile((r"^\s+" if indented else r"^") + re.escape(key) + r"\s*:")
    for i in range(start, stop if stop is not None else len(lines)):
        if pat.match(lines[i]):
            return i + 1
    return start + 1


@register
class ConfigKeyDrift(Rule):
    id = "YAMT005"
    name = "config-key-drift"
    description = (
        "a key in an apps/*.yml experiment file that no config.py dataclass field "
        "accepts — the static version of config._build's unknown-key error"
    )

    def check_project(self, project: Project) -> list[Finding]:
        schema = _config_schema(project)
        if schema is None or not project.yml_files:
            return []
        top, sections = schema
        import yaml

        findings: list[Finding] = []
        for path in project.yml_files:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            try:
                data = yaml.safe_load(text)
            except yaml.YAMLError as e:
                findings.append(Finding(path, 1, 0, self.id, f"unparseable YAML: {e}"))
                continue
            if not isinstance(data, dict):
                continue
            lines = text.splitlines()
            for key, value in data.items():
                if key == "_base_":
                    continue
                if key not in top:
                    line = _key_line(lines, str(key))
                    findings.append(
                        Finding(
                            path, line, 0, self.id,
                            f"unknown config key '{key}' (valid sections/fields: {sorted(top)})",
                        )
                    )
                    continue
                section = top[key]
                if section is None or not isinstance(value, dict):
                    continue
                valid = sections[section]
                sec_line = _key_line(lines, str(key))
                next_top = next(
                    (i for i in range(sec_line, len(lines)) if re.match(r"^[A-Za-z_]", lines[i])),
                    len(lines),
                )
                for sub in value:
                    if sub not in valid:
                        line = _key_line(lines, str(sub), sec_line, next_top, indented=True)
                        findings.append(
                            Finding(
                                path, line, 0, self.id,
                                f"unknown key '{key}.{sub}' (valid {section} fields: {sorted(valid)})",
                            )
                        )
        return findings
