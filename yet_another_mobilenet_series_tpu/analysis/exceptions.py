"""Escaping-exception-set summaries over the call graph (YAMT022's model).

For any function the rules ask about, :class:`ExceptionModel` computes the
set of exception TYPES that can escape a call to it, to fixpoint over the
resolved call graph (callgraph.py):

- ``raise X`` / ``raise X(...)`` / ``raise X from Y`` contribute the
  resolved class of ``X`` — a project :class:`~.symbols.ClassInfo` (keyed by
  its dotted qualname) or an external dotted name (``"ValueError"``,
  ``"json.JSONDecodeError"``);
- a bare ``raise`` (and ``raise e`` of the handler-bound name) re-raises the
  set the enclosing ``except`` actually absorbed;
- ``try/except`` narrows by the symbol-table class hierarchy: an exception
  passes a handler only when it is PROVABLY not a subclass of any caught
  type (project bases are walked structurally; external-vs-external falls
  back to the real builtin exception hierarchy, and anything still unknown
  absorbs — toward silence, never a guess);
- a bare/broad handler (``except:`` / ``except Exception``) absorbs
  everything, unless its body re-raises;
- calls add the callee's current escape set at the call site (so a raise
  three frames down still narrows through every ``try`` above it); opaque
  call targets, futures, and computed raise expressions contribute NOTHING.

Under-approximation is the contract: every degradation is toward a smaller
escape set, so a rule that flags an escaping type can trust it. The model is
demand-driven — only the call-closure of the functions a rule asks about is
walked, and each closure runs its own bounded fixpoint (the package-wide
sweep touches a few hundred functions, not every def in the tree).
"""

from __future__ import annotations

import ast
import builtins
from typing import Optional

from .core import qualified_name
from .symbols import ClassInfo

_MAX_ROUNDS = 12
_BROAD = ("Exception", "BaseException")

# the sentinel key for "a broad handler caught this": only used internally
# while narrowing, never escapes into a summary


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _builtin_exc(name: str):
    """The real builtin exception class behind a bare name, or None."""
    obj = getattr(builtins, _last(name), None)
    if isinstance(obj, type) and issubclass(obj, BaseException):
        return obj
    return None


class ExceptionModel:
    """Demand-driven escape-set summaries for one Project."""

    def __init__(self, project):
        self.project = project
        self.symbols = project.symbols
        self.cg = project.callgraph
        project.summaries  # converge returns-resolution before resolving calls
        self.classes: dict[str, ClassInfo] = {}  # key -> project class
        self._escapes: dict[str, frozenset[str]] = {}
        self._done: set[str] = set()
        self._callees: dict[str, list[str]] = {}  # qualname -> callee qualnames
        self._callee_at: dict[int, Optional[str]] = {}  # id(Call) -> qualname
        self._ancestors: dict[str, tuple[set[str], set[str], bool]] = {}

    # -- public -------------------------------------------------------------

    def escape_set(self, qualname: str) -> frozenset[str]:
        """Exception-type keys that can escape ``qualname`` (project classes
        by dotted qualname — see :attr:`classes` — externals by dotted
        name). Unknown functions escape nothing."""
        if qualname not in self._done:
            self._converge(qualname)
        return self._escapes.get(qualname, frozenset())

    def is_subtype(self, key: str, base: str) -> Optional[bool]:
        """True/False when the hierarchy answers, None when it cannot
        (external classes we never see the body of)."""
        return self._subtype(key, base)

    # -- fixpoint -----------------------------------------------------------

    def _converge(self, qualname: str) -> None:
        sub: list[str] = []
        seen: set[str] = set()
        stack = [qualname]
        while stack:
            q = stack.pop()
            if q in seen or q in self._done:
                continue
            seen.add(q)
            if q not in self.project.summaries:
                continue
            sub.append(q)
            stack.extend(self._callee_list(q))
        for _ in range(_MAX_ROUNDS):
            changed = False
            for q in sub:
                new = self._scan(q)
                if new != self._escapes.get(q, frozenset()):
                    self._escapes[q] = new
                    changed = True
            if not changed:
                break
        self._done.update(seen)

    def _callee_list(self, qualname: str) -> list[str]:
        got = self._callees.get(qualname)
        if got is not None:
            return got
        fi = self.project.summaries[qualname].fi
        src = fi.module.src
        out: list[str] = []
        for node in src.subtree(fi.node):
            if not isinstance(node, ast.Call):
                continue
            callee = self._resolve_callee(src, node)
            self._callee_at[id(node)] = callee
            if callee is not None:
                out.append(callee)
        self._callees[qualname] = out
        return out

    def _resolve_callee(self, src, call: ast.Call) -> Optional[str]:
        scope = self.cg.enclosing_scope(src, call)
        t = self.cg.resolve_call(src, call, scope)
        if t is None:
            return None
        if t.kind == "jit" and t.inner is not None:
            t = t.inner
        if t.kind == "function" and t.func is not None:
            return t.func.qualname
        if t.kind == "class" and "__init__" in t.cls.methods:
            return t.cls.methods["__init__"].qualname
        return None

    # -- one function's walk ------------------------------------------------

    def _scan(self, qualname: str) -> frozenset[str]:
        fi = self.project.summaries[qualname].fi
        self._callee_list(qualname)  # ensure call sites are resolved
        src = fi.module.src
        return frozenset(self._block(src, fi.node.body, frozenset(), {}))

    def _block(self, src, stmts, caught: frozenset[str],
               named: dict[str, frozenset[str]]) -> set[str]:
        out: set[str] = set()
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested defs escape only when called
            if isinstance(st, ast.Raise):
                out |= self._calls_in(st)
                out |= self._raised(src, st, caught, named)
            elif isinstance(st, ast.Try):
                out |= self._try(src, st, caught, named)
            else:
                out |= self._calls_in(st, skip_blocks=True)
                for block in ("body", "orelse", "finalbody"):
                    out |= self._block(src, getattr(st, block, []), caught, named)
                for case in getattr(st, "cases", []):
                    out |= self._block(src, case.body, caught, named)
        return out

    def _try(self, src, st: ast.Try, caught, named) -> set[str]:
        body = self._block(src, st.body, caught, named)
        out: set[str] = set()
        remaining = set(body)
        for h in st.handlers:
            catch = self._catch_keys(src, h)
            if catch is None:  # bare/broad/unresolvable: absorbs everything
                absorbed, remaining = remaining, set()
            else:
                absorbed = {e for e in remaining if not self._passes(e, catch)}
                remaining -= absorbed
            h_named = dict(named)
            if h.name:
                h_named[h.name] = frozenset(absorbed)
            out |= self._block(src, h.body, frozenset(absorbed), h_named)
        out |= remaining
        # else-block exceptions bypass this try's handlers (Python semantics)
        out |= self._block(src, st.orelse, caught, named)
        out |= self._block(src, st.finalbody, caught, named)
        return out

    def _raised(self, src, st: ast.Raise, caught, named) -> set[str]:
        if st.exc is None:  # bare raise: the handler's absorbed set
            return set(caught)
        expr = st.exc
        if isinstance(expr, ast.Name) and expr.id in named:
            return set(named[expr.id])  # `except X as e: ... raise e`
        if isinstance(expr, ast.Call):
            f = expr.func
            # `raise e.with_traceback(...)` re-raises the handler binding
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "with_traceback"
                and isinstance(f.value, ast.Name)
                and f.value.id in named
            ):
                return set(named[f.value.id])
            expr = f
        key = self._class_key(src, expr)
        return {key} if key is not None else set()

    def _calls_in(self, node, skip_blocks: bool = False) -> set[str]:
        """Callee escape contributions of every call under ``node`` (nested
        defs excluded; with ``skip_blocks`` the statement lists of compound
        statements are excluded too — the caller recurses into those)."""
        out: set[str] = set()
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(n, ast.Call):
                callee = self._callee_at.get(id(n))
                if callee is not None:
                    out |= self._escapes.get(callee, frozenset())
            for name, field in ast.iter_fields(n):
                if skip_blocks and name in ("body", "orelse", "finalbody", "handlers", "cases"):
                    continue
                if isinstance(field, ast.AST):
                    stack.append(field)
                elif isinstance(field, list):
                    stack.extend(x for x in field if isinstance(x, ast.AST))
        return out

    # -- type keys and hierarchy --------------------------------------------

    def _class_key(self, src, expr: ast.expr) -> Optional[str]:
        t = self.cg.resolve_expr(src, expr, self.cg.enclosing_scope(src, expr))
        if t is not None and t.kind == "class":
            self.classes[t.cls.qualname] = t.cls
            return t.cls.qualname
        q = qualified_name(expr, src.aliases)
        # external dotted name — but only a CamelCase tail reads as a CLASS
        # reference; a lowercase name is a variable holding a computed
        # exception (``raise mk()``), which must degrade to silence
        if q is not None and _last(q)[:1].isupper():
            return q
        return None

    def _catch_keys(self, src, h: ast.ExceptHandler) -> Optional[list[str]]:
        """Caught-type keys of one handler; None means "absorbs everything"
        (bare except, a broad type, or anything we cannot resolve)."""
        if h.type is None:
            return None
        elts = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        keys: list[str] = []
        for e in elts:
            key = self._class_key(src, e)
            if key is None or _last(key) in _BROAD:
                return None
            keys.append(key)
        return keys

    def _passes(self, exc: str, catch: list[str]) -> bool:
        """True only when ``exc`` PROVABLY escapes every caught type; an
        unknown relationship absorbs (under-approximation toward silence)."""
        return all(self._subtype(exc, c) is False for c in catch)

    def _subtype(self, exc: str, base: str) -> Optional[bool]:
        if _last(base) in _BROAD:
            return True
        if exc == base:
            return True
        if exc in self.classes:
            proj, ext, opaque = self._ancestry(exc)
            if base in self.classes:
                return True if base in proj else (None if opaque else False)
            b = _builtin_exc(base)
            for name in ext:
                if _last(name) == _last(base):
                    return True
                eb = _builtin_exc(name)
                if b is not None and eb is not None and issubclass(eb, b):
                    return True
            return None if opaque else False
        if base in self.classes:
            return False  # an external class cannot subclass a project one
        if _last(exc) == _last(base):
            return True
        e, b = _builtin_exc(exc), _builtin_exc(base)
        if e is not None and b is not None:
            return issubclass(e, b)
        return None  # two externals whose bodies we never see

    def _ancestry(self, key: str) -> tuple[set[str], set[str], bool]:
        """(project-ancestor keys, external-ancestor names, opaque-base?)
        of a project class — the class itself included in the first set."""
        got = self._ancestors.get(key)
        if got is not None:
            return got
        proj: set[str] = set()
        ext: set[str] = set()
        opaque = False
        self._ancestors[key] = (proj, ext, opaque)  # cycle guard
        stack = [key]
        while stack:
            k = stack.pop()
            if k in proj:
                continue
            proj.add(k)
            ci = self.classes.get(k)
            if ci is None:
                continue
            for b in ci.node.bases:
                bkey = self._class_key(ci.module.src, b)
                if bkey is None:
                    opaque = True
                elif bkey in self.classes:
                    stack.append(bkey)
                else:
                    ext.add(bkey)
        self._ancestors[key] = (proj, ext, opaque)
        return proj, ext, opaque
