"""yamt-lint command line.

Entry points (equivalent):

    python -m yet_another_mobilenet_series_tpu.analysis [paths...]
    python -m yet_another_mobilenet_series_tpu.cli.lint [paths...]

With no paths, lints the installed package itself. Exit codes: 0 clean,
1 findings, 2 usage error (argparse). JSON mode feeds scripts/lint.sh and CI.
"""

from __future__ import annotations

import argparse
import os
import sys

from .core import check_suppressions, load_rules, run_lint
from .reporters import render_github, render_json, render_text


def _default_path() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="yamt-lint",
        description="JAX/TPU tracing-safety and SPMD-contract static analyzer (docs/LINT.md)",
    )
    p.add_argument("paths", nargs="*", help="files or directories to lint (default: this package)")
    p.add_argument(
        "--format", choices=("text", "json", "github"), default="text",
        help="report format ('github' emits ::error workflow annotations for CI)",
    )
    p.add_argument("--select", default="", metavar="IDS", help="comma-separated rule ids to run (default: all)")
    p.add_argument(
        "--deselect", default="", metavar="IDS",
        help="comma-separated rule ids to skip (applied after --select; used by "
        "scripts/lint.sh --changed to drop the whole-package pairing rules, "
        "which would report every contract's absent other side on a partial "
        "file set)",
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule registry and exit")
    p.add_argument(
        "--check-suppressions", action="store_true",
        help="audit suppression comments instead of linting: a suppression whose "
        "rule no longer fires at its site is reported as YAMT900",
    )
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in load_rules():
            print(f"{rule.id}  {rule.name}\n    {rule.description}")
        return 0

    select = {s.strip().upper() for s in args.select.split(",") if s.strip()} or None
    deselect = {s.strip().upper() for s in args.deselect.split(",") if s.strip()}
    if deselect:
        select = (select if select is not None else {r.id for r in load_rules()}) - deselect
    runner = check_suppressions if args.check_suppressions else run_lint
    try:
        findings = runner(args.paths or [_default_path()], select=select)
    except (OSError, ValueError) as e:
        print(f"yamt-lint: {e}", file=sys.stderr)
        return 2
    renderer = {"json": render_json, "github": render_github, "text": render_text}[args.format]
    print(renderer(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
