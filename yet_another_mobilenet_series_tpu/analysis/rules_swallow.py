"""YAMT012 — silent broad-exception swallows in package code.

``except Exception: pass`` is how real failures become ghosts: the restore
path's legacy-retry bug (cli/train.py pre-robustness) treated EVERY restore
failure — including genuine checkpoint corruption — as a known benign shape
quirk, because a broad handler with no body cannot tell the difference and
tells no one. The rule: a handler that catches a BROAD exception class
(bare ``except:``, ``Exception``, ``BaseException``, or a tuple containing
one) must DO something — log, count, re-raise, return a fallback. A body
consisting only of ``pass``/``...`` is a silent swallow and is flagged.

Deliberately NOT flagged:

- **narrow handlers** (``except OSError: pass`` around ``os.unlink``): the
  author named the failure they are ignoring — that is a decision, not a
  blindfold;
- **``__del__`` finalizers**: raising in a finalizer only prints unraisable
  noise during interpreter shutdown; swallowing there is the sanctioned
  idiom (data/native_loader.py);
- handlers with ANY real statement — what the handler does is the author's
  policy; the rule only insists the swallow is visible in the code.

Scope: package code only (a directory holding ``__init__.py``), like
YAMT007/YAMT011 — standalone scripts and tests exempt. Intentional swallows
in package code carry a same-line suppression with a WHY comment
(docs/LINT.md house rule)::

    except Exception:  # yamt-lint: disable=YAMT012 — keep last good reading
        pass
"""

from __future__ import annotations

import ast
import os

from .core import Finding, Project, Rule, SourceFile, register

_BROAD = ("Exception", "BaseException")


def _is_broad(type_node: ast.expr | None) -> bool:
    if type_node is None:
        return True  # bare except
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(el) for el in type_node.elts)
    return isinstance(type_node, ast.Name) and type_node.id in _BROAD


def _is_silent(handler: ast.ExceptHandler) -> bool:
    """True when the handler body does nothing observable: only pass / ...
    statements (a docstring-style constant counts as nothing too)."""
    for st in handler.body:
        if isinstance(st, ast.Pass):
            continue
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Constant):
            continue  # bare Ellipsis or stray string literal
        return False
    return True


def _del_handler_ids(tree: ast.Module) -> set[int]:
    """Handlers living inside ``__del__`` methods — exempt (see docstring)."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "__del__":
            for sub in ast.walk(node):
                if isinstance(sub, ast.ExceptHandler):
                    out.add(id(sub))
    return out


@register
class SilentExceptionSwallow(Rule):
    id = "YAMT012"
    name = "silent-exception-swallow"
    description = (
        "a broad except (bare / Exception / BaseException) whose body is only "
        "pass: the failure disappears without a trace — log it, count it, "
        "re-raise it, or narrow the type to the failure you mean to ignore "
        "(__del__ finalizers exempt)"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        # package code only: a dir with __init__.py (scripts/tests exempt)
        if not os.path.exists(os.path.join(os.path.dirname(src.path), "__init__.py")):
            return []
        exempt = None
        findings: list[Finding] = []
        for node in src.nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not (_is_broad(node.type) and _is_silent(node)):
                continue
            if exempt is None:
                exempt = _del_handler_ids(src.tree)
            if id(node) in exempt:
                continue
            what = "bare except" if node.type is None else "broad except"
            findings.append(Finding(
                src.path, node.lineno, node.col_offset, self.id,
                f"{what} with a pass-only body silently swallows every failure: "
                "log/count/re-raise, or narrow the exception type to the one "
                "failure this means to ignore",
            ))
        return findings
