"""yamt-lint core: source model, rule registry, suppressions, runner.

The analyzer is pure AST — it never imports the code under analysis, so it
runs in milliseconds per file and cannot be broken by the very hazards it
hunts (a version-fragile jax import crashes ``import``, not ``ast.parse``).

Two rule shapes:

- file rules (``Rule.check_file``): one parsed module at a time, with the
  whole :class:`Project` available for cross-file context (e.g. the set of
  known mesh-axis constants);
- project rules (``Rule.check_project``): whole-tree invariants that have no
  single home file (dataclass/field-tuple contracts, YAML/config drift).

Suppressions are comment-driven, pylint-style::

    lax.psum(x, "data")  # yamt-lint: disable=YAMT003
    # yamt-lint: disable-file=YAMT001,YAMT002   (anywhere in the file)

``disable=all`` silences every rule for that line (or file).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Iterable, Sequence


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One lint hit, orderable into a stable (path, line, col) report."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


_SUPPRESS_RE = re.compile(
    r"#\s*yamt-lint:\s*disable(?P<scope>-file)?\s*=\s*(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


def _import_aliases(nodes) -> dict[str, str]:
    """Local binding -> dotted origin, from every import in the module.

    ``import numpy as np`` -> ``{'np': 'numpy'}``; ``from jax import lax`` ->
    ``{'lax': 'jax.lax'}``; relative imports keep their leading dots so they
    can never collide with an absolute ``jax.*``/``numpy.*`` match. Takes
    the already-walked node list so the file is traversed once, not twice.
    """
    aliases: dict[str, str] = {}
    for node in nodes:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                else:
                    top = a.name.split(".")[0]
                    aliases[top] = top
        elif isinstance(node, ast.ImportFrom):
            base = "." * node.level + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{base}.{a.name}" if base else a.name
    return aliases


def qualified_name(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """Dotted path of a Name/Attribute chain with import aliases resolved.

    ``lax.psum`` under ``from jax import lax`` -> ``'jax.lax.psum'``; returns
    None when the chain is not rooted in a plain name (call results,
    subscripts).
    """
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


class SourceFile:
    """One .py file: text, parsed tree, suppression table, import aliases."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.parse_error = e
        self._nodes: list[ast.AST] | None = None
        self._dfs: list[ast.AST] | None = None
        self._span: dict[int, tuple[int, int]] | None = None
        self._scopes: dict[int, ast.AST | None] | None = None
        self._parents: dict[int, ast.AST] | None = None
        self.aliases = _import_aliases(self.nodes) if self.tree is not None else {}
        self.line_suppressions: dict[int, set[str]] = {}
        self.file_suppressions: set[str] = set()
        self.file_suppression_lines: dict[str, int] = {}
        # tokenizing every file for suppression comments costs more than
        # parsing it; a file without the literal marker has none to find
        for lineno, comment in self._comments() if "yamt-lint" in text else ():
            m = _SUPPRESS_RE.search(comment)
            if m is None:
                continue
            rules = {r.strip().upper() for r in m.group("rules").split(",")}
            if m.group("scope"):
                self.file_suppressions |= rules
                for r in rules:
                    self.file_suppression_lines.setdefault(r, lineno)
            else:
                self.line_suppressions.setdefault(lineno, set()).update(rules)

    def _comments(self):
        """(lineno, text) of every real COMMENT token. Tokenizing (rather
        than regex-scanning raw lines) keeps suppression syntax QUOTED in a
        docstring or string literal from registering as a live suppression.
        Falls back to whole-line scanning only if tokenization fails."""
        try:
            return [
                (tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(io.StringIO(self.text).readline)
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return list(enumerate(self.lines, start=1))

    @property
    def nodes(self) -> list[ast.AST]:
        """Every AST node of the file in ``ast.walk`` (BFS) order, computed
        once and shared: ~20 rules re-walking every tree was the single
        biggest lint-time cost."""
        if self._nodes is None:
            self._nodes = [] if self.tree is None else list(ast.walk(self.tree))
        return self._nodes

    def subtree(self, node: ast.AST):
        """Every node of ``node``'s subtree (``node`` included) — the same
        node SET as ``ast.walk(node)``, served as a slice of a one-time
        DFS order of the whole tree instead of a fresh pure-Python re-walk
        (subtree walks were the analyzer's single hottest primitive).
        Contiguity is the invariant: a node's descendants occupy one
        contiguous segment of the DFS list. Iteration order differs from
        ``ast.walk`` (DFS vs BFS) — no consumer may depend on sibling
        order across depths. Nodes from another tree fall back to a real
        walk, never a wrong slice."""
        self._index()
        span = self._span.get(id(node))
        if span is None:
            return ast.walk(node)
        i, j = span
        return self._dfs[i:j]

    @property
    def scopes(self) -> dict[int, ast.AST | None]:
        """id(node) -> nearest enclosing FunctionDef/AsyncFunctionDef
        (None = module scope; a def's OWN scope is its enclosing one),
        filled during the same one-time DFS pass as :meth:`subtree`."""
        self._index()
        return self._scopes

    def _index(self) -> None:
        if self._dfs is not None:
            return
        order: list[ast.AST] = []
        spans: dict[int, tuple[int, int]] = {}
        scopes: dict[int, ast.AST | None] = {}
        if self.tree is not None:
            scopes[id(self.tree)] = None
            work: list = [self.tree]
            while work:
                n = work.pop()
                if type(n) is tuple:
                    spans[n[0]] = (n[1], len(order))
                    continue
                start = len(order)
                order.append(n)
                work.append((id(n), start))
                child_scope = (
                    n if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    else scopes[id(n)]
                )
                for child in ast.iter_child_nodes(n):
                    scopes[id(child)] = child_scope
                    work.append(child)
        self._dfs = order
        self._span = spans
        self._scopes = scopes

    @property
    def parents(self) -> dict[int, ast.AST]:
        """id(child) -> parent node for the whole tree, computed once
        (rules that walk upward — try/finally enclosure, statement
        context — were each rebuilding this map per file)."""
        if self._parents is None:
            parents: dict[int, ast.AST] = {}
            for node in self.nodes:
                for child in ast.iter_child_nodes(node):
                    parents[id(child)] = node
            self._parents = parents
        return self._parents

    def suppressed(self, finding: Finding) -> bool:
        for scope in (self.file_suppressions, self.line_suppressions.get(finding.line, ())):
            if "ALL" in scope or finding.rule.upper() in scope:
                return True
        return False


class Project:
    """Every parsed source + data file under the linted paths."""

    def __init__(self, files: Sequence[SourceFile], yml_files: Sequence[str] = ()):
        self.files = list(files)
        self.yml_files = list(yml_files)
        self._axis_constants: dict[str, str] | None = None
        self._symbols = None
        self._callgraph = None
        self._summaries = None
        self._concurrency = None
        self._contracts = None
        self._exceptions = None

    @property
    def symbols(self):
        """Project-wide symbol table (symbols.py), built once per Project."""
        if self._symbols is None:
            from .symbols import SymbolTable

            self._symbols = SymbolTable(self)
        return self._symbols

    @property
    def callgraph(self):
        """Intra-package call resolution (callgraph.py), built once."""
        if self._callgraph is None:
            from .callgraph import CallGraph

            self._callgraph = CallGraph(self)
        return self._callgraph

    @property
    def summaries(self):
        """qualname -> FunctionSummary (summaries.py). The dict is installed
        BEFORE the fixpoint runs so the call graph's returns-resolution can
        read partial results while they converge."""
        if self._summaries is None:
            from . import summaries as summaries_mod

            self._summaries = {}
            summaries_mod.compute(self, self._summaries)
            self._summaries_done = True  # callgraph memoization gate
        return self._summaries

    @property
    def concurrency(self):
        """Thread-root + lock-domain model (concurrency.py), built once."""
        if self._concurrency is None:
            from .concurrency import ConcurrencyModel

            self._concurrency = ConcurrencyModel(self)
        return self._concurrency

    @property
    def contracts(self):
        """Wire-contract extraction (contracts.py), built once per Project:
        headers, _ERROR_MAP, metric names/families, config schema."""
        if self._contracts is None:
            from .contracts import ContractModel

            self._contracts = ContractModel(self)
        return self._contracts

    @property
    def exceptions(self):
        """Escaping-exception-set summaries (exceptions.py), demand-driven
        over the call graph; built once per Project."""
        if self._exceptions is None:
            from .exceptions import ExceptionModel

            self._exceptions = ExceptionModel(self)
        return self._exceptions

    @property
    def axis_constants(self) -> dict[str, str]:
        """Known mesh axes across the project: constant name (or a synthetic
        ``Mesh axis '...'`` key) -> axis name. Ground truth for YAMT003, from
        two sources:

        - module-level ``X_AXIS = "name"`` string constants
          (``parallel/mesh.py`` ``DATA_AXIS`` in production);
        - axis-name literals in ``Mesh(devices, ('a', 'b'))`` construction
          calls (incl. the ``axis_names=`` keyword) — so a 2-D mesh whose
          second axis never gets its own constant still validates.
        """
        if self._axis_constants is None:
            consts: dict[str, str] = {}
            for src in self.files:
                if src.tree is None:
                    continue
                for node in src.tree.body:
                    if (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id.isupper()
                        and node.targets[0].id.endswith("_AXIS")
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)
                    ):
                        consts[node.targets[0].id] = node.value.value
                for node in src.nodes:
                    if not isinstance(node, ast.Call):
                        continue
                    q = qualified_name(node.func, src.aliases) or ""
                    if q.rsplit(".", 1)[-1] != "Mesh":
                        continue
                    axis_arg = node.args[1] if len(node.args) > 1 else next(
                        (kw.value for kw in node.keywords if kw.arg == "axis_names"), None
                    )
                    if axis_arg is None:
                        continue
                    elts = axis_arg.elts if isinstance(axis_arg, (ast.Tuple, ast.List)) else [axis_arg]
                    for el in elts:
                        if isinstance(el, ast.Constant) and isinstance(el.value, str):
                            consts.setdefault(f"Mesh axis {el.value!r}", el.value)
            self._axis_constants = consts
        return self._axis_constants


class Rule:
    """Base class; subclasses register with :func:`register` and implement
    ``check_file`` and/or ``check_project``."""

    id: str = ""
    name: str = ""
    description: str = ""

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        return []

    def check_project(self, project: Project) -> list[Finding]:
        return []


_REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    rule = cls()
    if not rule.id:
        raise ValueError(f"{cls.__name__} has no rule id")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id}")
    _REGISTRY[rule.id] = rule
    return cls


def load_rules() -> list[Rule]:
    """Import every rule module (registration side effect) and return the
    registry sorted by id."""
    from . import (  # noqa: F401
        rules_async_staging,
        rules_concurrency,
        rules_config,
        rules_contracts,
        rules_donation,
        rules_dtype,
        rules_imports,
        rules_logging,
        rules_prng_flow,
        rules_profiler,
        rules_recompile,
        rules_sockets,
        rules_spmd,
        rules_subprocess,
        rules_swallow,
        rules_threads,
        rules_time,
        rules_tracing,
    )

    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def collect_paths(paths: Iterable[str]) -> tuple[list[str], list[str]]:
    """Expand files/directories into (.py files, .yml files), stably sorted."""
    py: list[str] = []
    yml: list[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in ("__pycache__", ".git", ".pytest_cache"))
                for n in sorted(names):
                    full = os.path.join(root, n)
                    if n.endswith(".py"):
                        py.append(full)
                    elif n.endswith((".yml", ".yaml")):
                        yml.append(full)
        elif p.endswith(".py"):
            py.append(p)
        elif p.endswith((".yml", ".yaml")):
            yml.append(p)
        else:
            raise ValueError(f"not a directory, .py, or .yml path: {p}")
    return py, yml


def _load_project(paths: Iterable[str]) -> tuple[list[Finding], list[SourceFile], Project]:
    """Read and parse every linted path once: (syntax-error findings,
    parsed files, Project). Shared by :func:`run_lint` and
    :func:`check_suppressions` so the two stay byte-for-byte consistent."""
    py_paths, yml_paths = collect_paths(paths)
    syntax: list[Finding] = []
    files: list[SourceFile] = []
    for path in py_paths:
        with open(path, encoding="utf-8") as f:
            src = SourceFile(path, f.read())
        if src.parse_error is not None:
            e = src.parse_error
            syntax.append(
                Finding(path, e.lineno or 1, max((e.offset or 1) - 1, 0), "YAMT000", f"syntax error: {e.msg}")
            )
            continue
        files.append(src)
    return syntax, files, Project(files, yml_paths)


def _raw_findings(rules: Sequence[Rule], files: Sequence[SourceFile], project: Project) -> list[Finding]:
    """Every finding BEFORE suppression filtering (deduped)."""
    findings: list[Finding] = []
    for rule in rules:
        for src in files:
            findings.extend(rule.check_file(src, project))
        findings.extend(rule.check_project(project))
    # two roots reaching the same traced helper must not report it twice
    return sorted(set(findings))


def run_lint(paths: Iterable[str], select: set[str] | None = None) -> list[Finding]:
    """Lint ``paths`` (files or directories) and return sorted findings.

    ``select`` restricts to a set of rule ids (upper-case). Suppression
    comments are honored here, so callers only ever see live findings.
    """
    rules = load_rules()
    if select is not None:
        rules = [r for r in rules if r.id in select]
    findings, files, project = _load_project(paths)
    by_path = {src.path: src for src in files}

    def live(f: Finding) -> bool:
        # interprocedural rules may attribute a finding to a DIFFERENT file
        # than the one being checked (a traced helper in another module);
        # suppressions must be honored where the finding lands
        owner = by_path.get(f.path)
        return owner is None or not owner.suppressed(f)

    findings.extend(f for f in _raw_findings(rules, files, project) if live(f))
    return sorted(set(findings))


def check_suppressions(paths: Iterable[str], select: set[str] | None = None) -> list[Finding]:
    """Audit every suppression comment under ``paths``: a suppression whose
    rule no longer fires at its site is STALE — dead weight that silently
    swallows the rule if the hazard ever comes back at that line. Stale ones
    are reported as rule ``YAMT900`` findings (never themselves
    suppressible: the raw, pre-suppression findings are compared against).

    ``select`` limits which rules are re-run and judged; suppressions for
    rules outside the selection are left alone, not declared stale.
    """
    rules = load_rules()
    if select is not None:
        rules = [r for r in rules if r.id in select]
    judged = {r.id for r in rules}
    _, files, project = _load_project(paths)
    raw = _raw_findings(rules, files, project)
    at_line: dict[tuple[str, int], set[str]] = {}
    in_file: dict[str, set[str]] = {}
    for f in raw:
        at_line.setdefault((f.path, f.line), set()).add(f.rule)
        in_file.setdefault(f.path, set()).add(f.rule)

    out: list[Finding] = []
    for src in files:
        for lineno in sorted(src.line_suppressions):
            here = at_line.get((src.path, lineno), set())
            for r in sorted(src.line_suppressions[lineno]):
                if r == "ALL":
                    stale = not here
                elif r in judged:
                    stale = r not in here
                else:
                    continue
                if stale:
                    what = "no rule fires" if r == "ALL" else f"{r} no longer fires"
                    out.append(
                        Finding(
                            src.path, lineno, 0, "YAMT900",
                            f"stale suppression: {what} at this line; delete the "
                            "comment (it would silently swallow the rule if the "
                            "hazard returns)",
                        )
                    )
        for r in sorted(src.file_suppressions):
            if r == "ALL":
                stale = not in_file.get(src.path)
            elif r in judged:
                stale = r not in in_file.get(src.path, set())
            else:
                continue
            if stale:
                what = "no rule fires" if r == "ALL" else f"{r} never fires"
                out.append(
                    Finding(
                        src.path, src.file_suppression_lines.get(r, 1), 0, "YAMT900",
                        f"stale file-wide suppression: {what} anywhere in this "
                        "file; delete the disable-file comment",
                    )
                )
    return sorted(set(out))
