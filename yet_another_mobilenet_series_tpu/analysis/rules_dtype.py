"""YAMT016 — silent f32 upcast of a wire-typed (quantized) staging buffer.

The quantized serving path (serve/quant.py, serve.quant.wire="uint8") exists
to shrink every transferred byte: staging buffers, client batches, and AOT
signatures all carry a narrow WIRE dtype, and a single config flip moves the
whole request path between f32 and u8. The hazard that plumbing makes live
is the silent widening: one ``astype(np.float32)`` — or a dtype-forcing
``np.asarray(buf, np.float32)`` — on an array that was deliberately
allocated narrow quietly restores the 4x bytes the wire mode exists to
remove (and, worse, changes VALUES if the buffer held raw pixels the device
was going to denormalize). The engine/batcher route every conversion through
one ``wire_dtype`` resolved from config; this rule pins that discipline
wherever the idiom is written inline.

A local name is **wire-typed** when it is bound from an expression whose
dtype is explicitly narrow:

- an allocation with a narrow dtype: ``np.zeros/empty/ones/full/asarray/
  array/ascontiguousarray(..., <narrow>)`` (positional or ``dtype=``),
- a cast: ``x.astype(<narrow>)``,

where ``<narrow>`` is a uint8/int8/uint16/int16/float16/bfloat16 literal
(``np.uint8``, ``jnp.int8``, or the string ``"uint8"``...). The mark
propagates through plain rebinding, subscripts/slices (views share dtype),
and dtype-preserving methods (``reshape``/``ravel``/``copy``/
``transpose``/``view``); it clears when the name is rebound to anything
else or deleted. While a name is wire-typed, these conversions flag:

- ``name.astype(<f32>)`` — the explicit silent upcast,
- ``np/jnp.asarray|array(name, <f32>)`` (positional or ``dtype=``) — the
  dtype-forcing copy (the batcher's historical ``np.asarray(image,
  np.float32)`` literal was exactly this shape),
- dtype-LESS ``jnp.asarray(name)`` / ``jnp.array(name)`` — the conversion
  preserves whatever dtype arrives, which is the problem: it silently
  erases the wire contract at the host/device boundary instead of stating
  it (pass the wire dtype explicitly).

Conversions whose dtype argument is a *variable* (``np.asarray(img,
self._wire_dtype)``, ``buf.astype(wire)``) are the sanctioned idiom and
never flag — the rule targets literals, because a literal is what a config
flip cannot reach. Flow handling matches YAMT014: linear source order
within one function, loop bodies walked twice, branches not forked.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

# dtypes that mark a buffer as deliberately narrow (the wire side)
_NARROW = {"uint8", "int8", "uint16", "int16", "float16", "bfloat16"}
# dtypes whose literal use on a narrow buffer is the flagged upcast
_WIDE = {"float32", "float64"}

_ALLOC_FNS = {"zeros", "empty", "ones", "full", "asarray", "array", "ascontiguousarray"}
_NUMPY_ROOTS = {"numpy", "jax.numpy"}
# methods that preserve dtype: the mark rides through them
_PRESERVING = {"reshape", "ravel", "copy", "transpose", "view", "squeeze"}


def _np_root(q: str | None) -> str | None:
    """'numpy' / 'jax.numpy' when the dotted name is rooted there."""
    if not q:
        return None
    for root in _NUMPY_ROOTS:
        if q == root or q.startswith(root + "."):
            return root
    return None


def _dtype_class(node: ast.expr | None, aliases: dict) -> str | None:
    """'narrow' / 'wide' / None for a dtype-argument expression. Only
    LITERALS classify — a variable dtype is the sanctioned config-routed
    idiom and returns None."""
    name = None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    else:
        q = qualified_name(node, aliases) if node is not None else None
        if q is not None:
            name = q.rsplit(".", 1)[-1]
            if _np_root(q) is None and "." in q:
                return None  # some_module.uint8 that is not numpy/jnp
    if name in _NARROW:
        return "narrow"
    if name in _WIDE:
        return "wide"
    return None


def _call_dtype_arg(call: ast.Call, pos: int) -> ast.expr | None:
    """The dtype argument of an allocation/conversion call: ``dtype=`` or
    positional index ``pos``."""
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


class _Scanner:
    """Linear event interpreter for one scope (the YAMT014 shape): narrow
    marks, clearing rebinds, upcast findings deduped by location."""

    def __init__(self, rule: "SilentWireUpcast", src: SourceFile):
        self.rule = rule
        self.src = src
        self.marks: set[str] = set()
        self.out: dict[tuple, Finding] = {}

    def run(self, stmts) -> None:
        for st in stmts:
            self._stmt(st)

    # -- statements ---------------------------------------------------------

    def _stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            self._exprs(st.test if isinstance(st, ast.While) else st.iter)
            for _ in range(2):  # wrap-around: bottom-of-loop mark, top-of-loop use
                for s in st.body:
                    self._stmt(s)
            for s in st.orelse:
                self._stmt(s)
            return
        if isinstance(st, (ast.If, ast.Try, ast.With, ast.AsyncWith)):
            if isinstance(st, ast.If):
                self._exprs(st.test)
                blocks = [st.body, st.orelse]
            elif isinstance(st, ast.Try):
                blocks = [st.body, *[h.body for h in st.handlers], st.orelse, st.finalbody]
            else:
                for item in st.items:
                    self._exprs(item.context_expr)
                blocks = [st.body]
            for block in blocks:
                for s in block:
                    self._stmt(s)
            return
        if isinstance(st, ast.Assign):
            self._exprs(st.value)
            cls = self._expr_class(st.value)
            for t in st.targets:
                self._bind(t, cls)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._exprs(st.value)
                self._bind(st.target, self._expr_class(st.value))
            return
        if isinstance(st, ast.AugAssign):
            self._exprs(st.value)
            if isinstance(st.target, ast.Name):
                self.marks.discard(st.target.id)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    self.marks.discard(t.id)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._exprs(child)

    def _bind(self, target: ast.expr, cls: str | None) -> None:
        if isinstance(target, ast.Name):
            if cls == "narrow":
                self.marks.add(target.id)
            else:
                self.marks.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, None)  # tuple unpack: conservatively clear
        elif isinstance(target, ast.Starred):
            self._bind(target.value, None)

    # -- expression classification -----------------------------------------

    def _expr_class(self, expr: ast.expr) -> str | None:
        """'narrow' when the expression produces a wire-typed array (and so
        its binding target should carry the mark)."""
        # plain rebinding / views / dtype-preserving methods propagate
        if isinstance(expr, ast.Name):
            return "narrow" if expr.id in self.marks else None
        if isinstance(expr, ast.Subscript):
            return self._expr_class(expr.value)
        if isinstance(expr, ast.Call):
            f = expr.func
            # buf.reshape(...) etc. on a marked name
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _PRESERVING
                and isinstance(f.value, ast.Name)
                and f.value.id in self.marks
            ):
                return "narrow"
            # x.astype(<narrow>)
            if isinstance(f, ast.Attribute) and f.attr == "astype":
                if _dtype_class(_call_dtype_arg(expr, 0), self.src.aliases) == "narrow":
                    return "narrow"
                return None
            # np.zeros(..., <narrow>) and friends
            q = qualified_name(f, self.src.aliases)
            root = _np_root(q)
            if root is not None and q.rsplit(".", 1)[-1] in _ALLOC_FNS:
                pos = 1  # dtype is the 2nd positional for every _ALLOC_FNS member
                if _dtype_class(_call_dtype_arg(expr, pos), self.src.aliases) == "narrow":
                    return "narrow"
        return None

    # -- uses (the findings) ------------------------------------------------

    def _exprs(self, expr: ast.expr | None) -> None:
        if expr is None:
            return
        for node in self.src.subtree(expr):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, (ast.Lambda,)):
                continue
            self._check_call(node)

    def _check_call(self, call: ast.Call) -> None:
        f = call.func
        # name.astype(<f32 literal>)
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "astype"
            and isinstance(f.value, ast.Name)
            and f.value.id in self.marks
            and _dtype_class(_call_dtype_arg(call, 0), self.src.aliases) == "wide"
        ):
            self._flag(f.value.id, call, "astype")
            return
        q = qualified_name(f, self.src.aliases)
        root = _np_root(q)
        if root is None or q.rsplit(".", 1)[-1] not in ("asarray", "array", "ascontiguousarray"):
            return
        if not (call.args and isinstance(call.args[0], ast.Name) and call.args[0].id in self.marks):
            return
        dt = _call_dtype_arg(call, 1)
        cls = _dtype_class(dt, self.src.aliases)
        if cls == "wide":
            self._flag(call.args[0].id, call, "forced-f32 conversion")
        elif dt is None and root == "jax.numpy" and q.rsplit(".", 1)[-1] in ("asarray", "array"):
            # the dtype-less device conversion: erases the wire contract at
            # the host/device boundary instead of stating it
            self._flag(call.args[0].id, call, "dtype-less device conversion")

    def _flag(self, name: str, node: ast.AST, what: str) -> None:
        f = Finding(
            self.src.path, node.lineno, node.col_offset, self.rule.id,
            f"{what} of wire-typed buffer '{name}': the quantized serving wire "
            "deliberately allocated it narrow, and a literal f32 (or dtype-less "
            "device) conversion silently restores 4x the bytes — route the "
            "dtype through one config-resolved wire_dtype variable instead "
            "(serve/quant.py discipline)",
        )
        self.out.setdefault((f.line, f.col, name), f)


@register
class SilentWireUpcast(Rule):
    id = "YAMT016"
    name = "silent-wire-upcast"
    description = (
        "array deliberately allocated/cast to a narrow wire dtype is converted "
        "back to f32 with a literal dtype (or a dtype-less jnp.asarray): the "
        "silent widening un-does the quantized serving wire — pass the "
        "config-resolved wire dtype explicitly (serve/engine.py + "
        "serve/batcher.py are the sanctioned idiom)"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        scopes: list[ast.AST] = [src.tree]
        scopes += [
            n for n in src.nodes
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            scanner = _Scanner(self, src)
            scanner.run(scope.body)
            findings.extend(scanner.out.values())
        return findings
