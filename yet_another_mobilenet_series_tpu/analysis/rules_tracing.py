"""Tracing-safety rules.

YAMT001 — host-side effects inside jit/shard_map-traced functions. A
``print``/``time.time()``/``np.random.*`` call under trace runs ONCE at trace
time (or forces a host sync via ``.item()``), silently breaking the
single-XLA-program contract of train/steps.py. A function is "traced" when it
is decorated with a tracing transform (``@jax.jit``,
``@partial(jax.jit, ...)``, ``@jax.checkpoint``) or passed to one
(``jax.jit(f)``, ``shard_map(f, ...)``, ``jax.grad(f)``,
``lax.scan(f, ...)``, ...) — since the interprocedural PR including
attribute-call and factory-result arguments (``jax.jit(trainer.step)``,
``jax.jit(make_prune_event(...))``), resolved through the project call graph
into ANY linted module. Nested ``def``s inside a traced function are traced
too, and so is every resolved callee: a call inside a traced body executes
under trace, so the scan follows it (opaque calls stay skipped). A function
containing a mesh collective (``lax.psum``/``pmean``/``axis_index``/...) is
also a traced context — collectives only execute under trace — which catches
step builders whose inner ``step_fn`` is returned and jitted in ANOTHER
module (train/steps.py -> parallel/dp.py).

YAMT002 — PRNG key discipline. A key consumed by two or more ``jax.random``
draws without an intervening ``split``/``fold_in`` (or reassignment) yields
CORRELATED randomness — dropout masks equal to augmentation noise, identical
mixup permutations across uses. Also flags a draw inside a loop whose key was
bound outside the loop (every iteration reuses the same key) — including
comprehension/generator bodies (``[jax.random.normal(key) for ...]``), which
iterate exactly like a ``for`` but sat outside the loop detection until the
observability PR closed the ROADMAP-deferred gap. Scans every function (and
the module body); ``if``/``try`` branches are analyzed separately and merged,
so mutually-exclusive draws don't false-positive.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

# tracing entry points: resolved qualified name -> positions of traced
# callables among the positional args
_TRACE_ENTRY: dict[str, tuple[int, ...]] = {
    "jax.jit": (0,),
    "jax.pmap": (0,),
    "jax.vmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.map": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.custom_root": (0, 1, 2),
    "jax.custom_vjp": (0,),
    "jax.custom_jvp": (0,),
}
# these two move across modules/wrappers (utils/compat.py, pallas), so they
# match on the last path component wherever they were imported from
_TRACE_TAIL = {"shard_map", "pallas_call"}

_HOST_CALL_NAMES = {"print", "input", "breakpoint", "open"}
_HOST_PREFIXES = ("time.", "numpy.random.", "random.", "datetime.")
_HOST_METHODS = {"item", "tolist", "to_py"}


def _is_trace_entry(q: str) -> bool:
    return q in _TRACE_ENTRY or q.split(".")[-1] in _TRACE_TAIL


def _trace_arg_indices(q: str) -> tuple[int, ...]:
    if q in _TRACE_ENTRY:
        return _TRACE_ENTRY[q]
    if q.split(".")[-1] in _TRACE_TAIL:
        return (0,)
    return ()


def _arg_names(fn: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda) -> set[str]:
    a = fn.args
    return {x.arg for x in (*a.posonlyargs, *a.args, *a.kwonlyargs)} | {
        x.arg for x in (a.vararg, a.kwarg) if x is not None
    }


def _resolved_function(cg, src, expr, scope):
    """Project FunctionInfo behind an expression (unwrapping one jit layer),
    or None when the call graph can't resolve it."""
    t = cg.resolve_expr(src, expr, scope)
    if t is None:
        return None
    if t.kind == "jit" and t.inner is not None:
        t = t.inner
    return t.func if t.kind == "function" else None


def _directly_contains_collective(fn_node, aliases, collectives) -> bool:
    """A collective in the function's OWN body (nested defs excluded — they
    make their own root decision; the enclosing factory runs on the host)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call) and qualified_name(n.func, aliases) in collectives:
            return True
        stack.extend(ast.iter_child_nodes(n))
    return False


@register
class HostEffectsUnderTrace(Rule):
    id = "YAMT001"
    name = "host-effect-under-trace"
    description = (
        "print/time/np.random/.item() inside a jit- or shard_map-traced function: "
        "runs at trace time only (or forces a host sync), breaking the one-XLA-program step"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        from .rules_spmd import _COLLECTIVES

        cg = project.callgraph
        tree, aliases = src.tree, src.aliases
        defs_by_name: dict[str, list[ast.AST]] = {}
        for node in src.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)

        # roots: (node, SourceFile) — the interprocedural layer can resolve a
        # traced callable into ANOTHER module (jax.jit(trainer.step),
        # jax.jit(make_prune_event(...)))
        roots: list[tuple[ast.AST, SourceFile]] = []
        for node in src.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a body with a mesh collective DIRECTLY in it (not via a
                # nested def — a factory's build-time code is host code) is a
                # traced context by construction, however it reaches jit
                if _directly_contains_collective(node, aliases, _COLLECTIVES):
                    roots.append((node, src))
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    q = qualified_name(target, aliases)
                    if q and _is_trace_entry(q):
                        roots.append((node, src))
                    elif (
                        isinstance(dec, ast.Call)
                        and qualified_name(dec.func, aliases) in ("functools.partial", "partial")
                        and dec.args
                    ):
                        q2 = qualified_name(dec.args[0], aliases)
                        if q2 and _is_trace_entry(q2):
                            roots.append((node, src))
            elif isinstance(node, ast.Call):
                q = qualified_name(node.func, aliases)
                if not q:
                    continue
                for i in _trace_arg_indices(q):
                    if i < len(node.args):
                        arg = node.args[i]
                        if isinstance(arg, ast.Lambda):
                            roots.append((arg, src))
                        elif isinstance(arg, ast.Name) and arg.id in defs_by_name:
                            roots.extend((d, src) for d in defs_by_name[arg.id])
                        else:
                            # attribute / cross-module / factory-result arg:
                            # resolve through the call graph
                            fi = _resolved_function(cg, src, arg, cg.enclosing_scope(src, node))
                            if fi is not None:
                                roots.append((fi.node, fi.module.src))

        findings: dict[tuple, Finding] = {}
        visited: set[int] = set()
        # one finding per location; inner defs processed last so the most
        # specific function name wins when roots nest (factory + inner step)
        unique = {id(r): (r, s) for r, s in roots}
        for root, rsrc in sorted(unique.values(), key=lambda rs: (rs[1].path != src.path, rs[0].lineno)):
            fname = getattr(root, "name", "<lambda>")
            visited.add(id(root))  # a recursive traced fn must not loop _follow
            self._scan(root, fname, _arg_names(root), rsrc, findings, cg, visited)
        return list(findings.values())

    def _scan(self, node, fname, params, src, out, cg, visited, scope=None):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            params = params | _arg_names(node)
            scope = node
        if isinstance(node, ast.Call):
            self._check_call(node, fname, params, src.aliases, src.path, out)
            self._follow(node, src, out, cg, visited, scope)
        for child in ast.iter_child_nodes(node):
            self._scan(child, fname, params, src, out, cg, visited, scope)

    def _follow(self, call, src, out, cg, visited, scope):
        """A call inside a traced body executes under trace too: descend into
        the resolved callee (any module) and scan it with ITS own context.
        Unresolvable calls stay opaque — no guess, no crash."""
        fi = _resolved_function(cg, src, call.func, scope)
        if fi is None or id(fi.node) in visited:
            return
        visited.add(id(fi.node))
        self._scan(
            fi.node, fi.name, fi.all_params, fi.module.src, out, cg, visited, scope=fi.node
        )

    def _check_call(self, node: ast.Call, fname, params, aliases, path, out):
        def flag(msg):
            out[(path, node.lineno, node.col_offset)] = Finding(path, node.lineno, node.col_offset, self.id, msg)

        func = node.func
        if isinstance(func, ast.Name):
            if func.id in _HOST_CALL_NAMES:
                alt = " (use jax.debug.print for traced values)" if func.id == "print" else ""
                flag(f"host call `{func.id}(...)` inside traced function '{fname}'{alt}")
            elif (
                func.id in ("float", "int", "bool")
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in params
            ):
                flag(
                    f"`{func.id}({node.args[0].id})` on a traced argument of '{fname}' "
                    "forces a host sync (ConcretizationTypeError under jit)"
                )
        elif isinstance(func, ast.Attribute):
            if func.attr in _HOST_METHODS:
                flag(
                    f"`.{func.attr}()` inside traced function '{fname}' forces a host "
                    "sync; keep values on device or move the readback outside the step"
                )
            q = qualified_name(func, aliases)
            if q and q.startswith(_HOST_PREFIXES):
                flag(
                    f"host-side `{q}(...)` inside traced function '{fname}': executes at "
                    "trace time only, not per step (use jax primitives or hoist it out)"
                )


_KEY_SAFE = {"split", "fold_in", "PRNGKey", "key", "wrap_key_data", "key_data", "clone"}
_KEY_PARAM_RE = re.compile(r"(^|_)(rng|key|prng)s?($|_)")


class _KeyState:
    """Per-scope PRNG bookkeeping: name -> [draw_count, binding_loop_depth]."""

    def __init__(self, seed_names=(), depth=0):
        self.vars: dict[str, list[int]] = {n: [0, depth] for n in seed_names}

    def copy(self):
        s = _KeyState()
        s.vars = {k: list(v) for k, v in self.vars.items()}
        return s

    def merge(self, *branches):
        names = set(self.vars)
        for b in branches:
            names |= set(b.vars)
        merged = {}
        for n in names:
            ents = [b.vars[n] for b in branches if n in b.vars] or [self.vars[n]]
            merged[n] = [max(e[0] for e in ents), min(e[1] for e in ents)]
        self.vars = merged


@register
class PRNGKeyReuse(Rule):
    id = "YAMT002"
    name = "prng-key-reuse"
    description = (
        "a PRNG key consumed by >=2 jax.random draws (or re-drawn inside a loop or "
        "comprehension) without an intervening split/fold_in: correlated randomness"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        out: dict[tuple, Finding] = {}
        scopes: list[tuple[ast.AST, set[str]]] = [(src.tree, set())]
        for node in src.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                seeds = {n for n in _arg_names(node) if _KEY_PARAM_RE.search(n)}
                scopes.append((node, seeds))
        for scope, seeds in scopes:
            # current scope for subclasses that resolve calls (YAMT010)
            self._scope = scope if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)) else None
            state = _KeyState(seeds)
            self._block(list(getattr(scope, "body", [])), state, 0, src, out)
        return list(out.values())

    # -- statement walk ----------------------------------------------------

    def _block(self, stmts, state, depth, src, out) -> bool:
        """Process a statement list; True if it ends control flow (so a
        terminated `if` branch must not merge into the fall-through state —
        a draw after `if x: return draw(rng)` is NOT a second consumption)."""
        for st in stmts:
            self._stmt(st, state, depth, src, out)
            if isinstance(st, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
                return True
        return False

    def _stmt(self, st, state, depth, src, out):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate scope, driven from check_file
        if isinstance(st, ast.If):
            self._consume(st.test, state, depth, src, out)
            b1, b2 = state.copy(), state.copy()
            t1 = self._block(st.body, b1, depth, src, out)
            t2 = self._block(st.orelse, b2, depth, src, out)
            live = [b for b, t in ((b1, t1), (b2, t2)) if not t]
            if live:
                state.merge(*live)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._consume(st.iter, state, depth, src, out)
            self._reset_targets(st.target, state, depth + 1)
            body = state.copy()
            self._block(st.body, body, depth + 1, src, out)
            els = state.copy()
            self._block(st.orelse, els, depth, src, out)
            state.merge(body, els)
        elif isinstance(st, ast.While):
            self._consume(st.test, state, depth, src, out)
            body = state.copy()
            self._block(st.body, body, depth + 1, src, out)
            state.merge(body)
        elif isinstance(st, ast.Try):
            branches = []
            for block in (st.body, *[h.body for h in st.handlers], st.orelse):
                b = state.copy()
                terminated = self._block(block, b, depth, src, out)
                if not terminated:
                    branches.append(b)
            if branches:
                state.merge(*branches)
            self._block(st.finalbody, state, depth, src, out)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._consume(item.context_expr, state, depth, src, out)
            self._block(st.body, state, depth, src, out)
        elif isinstance(st, ast.Assign):
            self._consume(st.value, state, depth, src, out)
            for t in st.targets:
                self._reset_targets(t, state, depth)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            if st.value is not None:
                self._consume(st.value, state, depth, src, out)
            self._reset_targets(st.target, state, depth)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                self._reset_targets(t, state, depth)
        else:
            for expr in ast.iter_child_nodes(st):
                if isinstance(expr, ast.expr):
                    self._consume(expr, state, depth, src, out)

    def _reset_targets(self, target, state, depth):
        if isinstance(target, ast.Name):
            state.vars[target.id] = [0, depth]
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._reset_targets(el, state, depth)
        elif isinstance(target, ast.Starred):
            self._reset_targets(target.value, state, depth)

    # -- expression consumption --------------------------------------------

    def _consume(self, expr, state, depth, src, out):
        """Recursive in-evaluation-order walk; a ternary's arms are merged
        like `if` branches (exactly one executes), lambdas are deferred
        bodies and skipped."""
        if expr is None or isinstance(expr, ast.Lambda):
            return
        if isinstance(expr, ast.IfExp):
            self._consume(expr.test, state, depth, src, out)
            b1, b2 = state.copy(), state.copy()
            self._consume(expr.body, b1, depth, src, out)
            self._consume(expr.orelse, b2, depth, src, out)
            state.merge(b1, b2)
            return
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            # a comprehension is a loop: its element expression evaluates
            # once per iteration, so a draw there off a key bound OUTSIDE it
            # reuses that key per element. The first iterable evaluates once
            # (outer scope); targets rebind at loop depth each iteration.
            self._consume(expr.generators[0].iter, state, depth, src, out)
            inner = state.copy()
            d2 = depth + 1
            for i, gen in enumerate(expr.generators):
                self._reset_targets(gen.target, inner, d2)
                if i > 0:  # nested generators' iterables re-evaluate per outer element
                    self._consume(gen.iter, inner, d2, src, out)
                for cond in gen.ifs:
                    self._consume(cond, inner, d2, src, out)
            if isinstance(expr, ast.DictComp):
                self._consume(expr.key, inner, d2, src, out)
                self._consume(expr.value, inner, d2, src, out)
            else:
                self._consume(expr.elt, inner, d2, src, out)
            state.merge(inner)
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, (ast.expr, ast.keyword)):
                self._consume(child if isinstance(child, ast.expr) else child.value, state, depth, src, out)
        if isinstance(expr, ast.Call):
            self._check_draw(expr, state, depth, src, out)

    def _check_draw(self, call, state, depth, src, out):
        q = qualified_name(call.func, src.aliases)
        if not q or not q.startswith("jax.random."):
            return
        fn = q.rsplit(".", 1)[-1]
        if fn in _KEY_SAFE:
            return
        if not call.args or not isinstance(call.args[0], ast.Name):
            return
        name = call.args[0].id
        ent = state.vars.get(name)
        if ent is None:
            # first sight (closure/implicit binding): bind at current depth
            state.vars[name] = [1, depth]
            return
        if depth > ent[1]:
            f = Finding(
                src.path, call.lineno, call.col_offset, self.id,
                f"PRNG key '{name}' (bound outside this loop/comprehension) is consumed "
                f"by jax.random.{fn} every iteration; fold_in the loop index or split first",
            )
            out.setdefault((f.line, name), f)
            return
        ent[0] += 1
        if ent[0] == 2:
            f = Finding(
                src.path, call.lineno, call.col_offset, self.id,
                f"PRNG key '{name}' consumed by a second jax.random draw "
                f"(jax.random.{fn}) without an intervening split/fold_in: "
                "the two draws are perfectly correlated",
            )
            out.setdefault((f.line, name), f)
