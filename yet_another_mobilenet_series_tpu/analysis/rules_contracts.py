"""Wire-contract rules (YAMT022-025) on top of contracts.py and
exceptions.py (docs/LINT.md "Contract rules").

All four are project rules: each contract has a sending side and a
receiving side in different files (often different PROCESSES), so no
single-file check can see the drift. Scope matches YAMT019-021: package
code only (a dir with ``__init__.py``).
"""

from __future__ import annotations

from .concurrency import is_package_code
from .contracts import Site
from .core import Finding, Project, Rule, register


@register
class UnmappedEscapingException(Rule):
    id = "YAMT022"
    name = "unmapped-escaping-exception"
    description = (
        "a typed project exception can escape a serve submit path with no "
        "_ERROR_MAP entry: the verdict degrades to a generic 500 crossing the tier"
    )

    def check_project(self, project: Project) -> list[Finding]:
        em = project.contracts.error_map
        if em is None:
            return []
        exc_model = project.exceptions
        covered = list(dict.fromkeys(em.mapped)) + sorted(em.handled)
        out: list[Finding] = []
        seen: set[tuple[str, str]] = set()
        for mi in project.symbols.modules.values():
            if not is_package_code(mi.src.path):
                continue
            for ci in mi.classes.values():
                fi = ci.methods.get("submit")
                if fi is None:
                    continue
                for key in sorted(exc_model.escape_set(fi.qualname)):
                    if key not in exc_model.classes:
                        continue  # external types: out of this contract
                    # covered when it IS (or may be) a subtype of a mapped
                    # or hand-dispatched class — uncertainty stays silent
                    if any(exc_model.is_subtype(key, c) is not False for c in covered):
                        continue
                    if (fi.qualname, key) in seen:
                        continue
                    seen.add((fi.qualname, key))
                    exc_cls = exc_model.classes[key]
                    out.append(
                        Finding(
                            mi.src.path, fi.node.lineno, 0, self.id,
                            f"{exc_cls.name} (defined at {exc_cls.module.src.path}:"
                            f"{exc_cls.node.lineno}) can escape {ci.name}.submit but has "
                            f"no _ERROR_MAP entry ({em.path}:{em.line}): the frontend "
                            "degrades it to a generic 500 and the typed verdict is lost "
                            "crossing the tier; add a row (or catch it on the submit "
                            "path), or suppress with the sanctioned-idiom reason "
                            "(docs/LINT.md)",
                        )
                    )
        return out


@register
class WireHeaderDrift(Rule):
    id = "YAMT023"
    name = "wire-header-drift"
    description = (
        "a custom wire header is sent with no receiving-side parse, or parsed "
        "but never sent (dead parse)"
    )

    def check_project(self, project: Project) -> list[Finding]:
        c = project.contracts
        if not c.headers_sent and not c.headers_parsed:
            return []
        out: list[Finding] = []
        for name in sorted(set(c.headers_sent) - set(c.headers_parsed)):
            site = min(c.headers_sent[name], key=lambda s: (s.path, s.line))
            out.append(
                Finding(
                    site.path, site.line, 0, self.id,
                    f"header '{name}' is sent here but no receiving side parses it "
                    "(no headers.get/getheader/subscript read anywhere in the "
                    "package): the bytes cross the wire and die; parse it on the "
                    "receiving tier or stop sending it",
                )
            )
        for name in sorted(set(c.headers_parsed) - set(c.headers_sent)):
            site = min(c.headers_parsed[name], key=lambda s: (s.path, s.line))
            out.append(
                Finding(
                    site.path, site.line, 0, self.id,
                    f"header '{name}' is parsed here but no sending side ever sets "
                    "it: a dead parse that reads as a live contract; set it on the "
                    "sending tier or delete the parse",
                )
            )
        return out


@register
class MetricDrift(Rule):
    id = "YAMT024"
    name = "metric-drift"
    description = (
        "a registry metric name is emitted but absent from the OBSERVABILITY.md "
        "taxonomy, or a dotted per-label family is missing from PROM_LABEL_FAMILIES"
    )

    def check_project(self, project: Project) -> list[Finding]:
        c = project.contracts
        out: list[Finding] = []

        def first(sites: list[Site]) -> Site:
            return min(sites, key=lambda s: (s.path, s.line))

        fams = c.prom_families or set()
        for name in sorted(c.metric_literals):
            site = first(c.metric_literals[name])
            doc = c.doc_for(site.path)
            if doc is None:
                continue
            # a literal that samples a registered family ("fleet.slo_burn_
            # rate.short") is judged by its family's doc row, not its own
            fam = next(
                (f for f in fams if name.startswith(f + ".")), None)
            if not c.documented(fam or name, doc):
                out.append(
                    Finding(
                        site.path, site.line, 0, self.id,
                        f"metric '{name}' is emitted here but absent from the "
                        f"{_rel(doc)} taxonomy: an operator reading the docs never "
                        "learns it exists; add a taxonomy row (or rename to a "
                        "documented name)",
                    )
                )
        for fam in sorted(c.metric_families):
            site = first(c.metric_families[fam])
            if c.prom_families is not None and fam not in c.prom_families:
                out.append(
                    Finding(
                        site.path, site.line, 0, self.id,
                        f"per-label metric family '{fam}.<label>' is emitted here "
                        "but missing from PROM_LABEL_FAMILIES (obs/registry.py): "
                        "every sample renders as its own unlabeled series on "
                        "/metrics instead of one labeled family; register the "
                        "family prefix with its label name",
                    )
                )
            doc = c.doc_for(site.path)
            if doc is not None and not c.documented(fam, doc):
                out.append(
                    Finding(
                        site.path, site.line, 0, self.id,
                        f"metric family '{fam}.<label>' is emitted here but absent "
                        f"from the {_rel(doc)} taxonomy; add a taxonomy row",
                    )
                )
        return out


@register
class ConfigDrift(Rule):
    id = "YAMT025"
    name = "config-drift"
    description = (
        "a config dataclass section is not registered in _SECTION_TYPES, or a "
        "config field is never read by package code"
    )

    def check_project(self, project: Project) -> list[Finding]:
        schema = project.contracts.config
        if schema is None:
            return []
        out: list[Finding] = []
        for owner, field, ann, line in schema.section_fields:
            if ann in schema.registered:
                continue
            out.append(
                Finding(
                    schema.path, line, 0, self.id,
                    f"config section '{owner}.{field}: {ann}' is not registered in "
                    f"_SECTION_TYPES ({schema.path}:{schema.registry_line}): every "
                    f"dotted override of a {ann} field raises TypeError at build "
                    "time (the PR 18 zoo bug); add the class to _SECTION_TYPES",
                )
            )
        reads = project.contracts.attr_reads
        for owner, field, line in schema.plain_fields:
            if field in reads:
                continue
            out.append(
                Finding(
                    schema.path, line, 0, self.id,
                    f"config field '{owner}.{field}' is never read by package code "
                    "(no attribute access or getattr anywhere outside the schema "
                    "module): dead configuration that reads as a live knob; wire "
                    "it up or delete it, or suppress with the consumer's location "
                    "if it is read outside the package (docs/LINT.md)",
                )
            )
        return out


def _rel(path: str) -> str:
    parts = path.replace("\\", "/").split("/")
    return "/".join(parts[-2:])
