"""YAMT008 — donated-buffer reuse (the top ROADMAP-deferred lint rule).

``jax.jit(f, donate_argnums=(0,))`` lets XLA overwrite the donated argument's
buffer in place — after the call that buffer is DELETED, and any later read
of the variable dies at runtime with "Array has been deleted" (or worse,
only on the hardware where donation is actually implemented, so CPU tests
pass and the TPU run dies). The live hazards this rule guards are
cli/train.py's donated TrainState (``ts`` must be rebound by every dispatch)
and the serving engine's donated input batch (serve/engine.py).

Detection is linear-flow: a name bound to ``jax.jit(...)``/``jax.pmap(...)``
with ``donate_argnums`` is a *donating function*; after a call ``f(a, b)``
passes variable ``a`` at a donated position, any read of ``a`` before a
rebinding is flagged. The rebind-in-the-same-statement idiom
(``ts, m = step(ts, batch)``) is clean by construction — the call marks the
donation, the assignment targets clear it. Loop bodies are walked twice so a
donation at the bottom of an iteration flags a read at the top of the next.

Since the interprocedural PR, donors also resolve through the call graph
(callgraph.py) and the per-function summaries (summaries.py): attribute
calls on locally-constructed or annotated instances
(``trainer.train_step(ts, b)`` where ``Trainer.__init__`` binds a donating
jit), names bound to factory RESULTS (``step = make_dp_train_step(...)``
whose summary returns ``jit(..., donate_argnums=(0,))`` — the live
cli/train.py shape), and calls to project functions whose summaries donate a
parameter transitively. Opaque calls are still skipped — a donation is never
guessed.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

_DONATING_WRAPPERS = {"jax.jit", "jax.pmap"}


def _call_label(func: ast.expr) -> str:
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)) or "<call>"


def _donated_indices(call: ast.Call) -> tuple[int, ...] | None:
    """Static donate_argnums of a jax.jit/pmap call, or None if absent/dynamic."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int) for e in v.elts
        ):
            return tuple(e.value for e in v.elts)
        return None  # computed donate_argnums: not statically checkable
    return None


@register
class DonatedBufferReuse(Rule):
    id = "YAMT008"
    name = "donated-buffer-reuse"
    description = (
        "a variable read after being passed at a donated position of a "
        "jit(..., donate_argnums=...) call: the buffer is deleted after dispatch "
        "(runtime 'Array has been deleted', possibly only on hardware with real donation)"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        self._project = project
        self._cg = project.callgraph
        donors: dict[str, tuple[int, ...]] = {}
        for node in src.nodes:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                continue
            if qualified_name(node.value.func, src.aliases) in _DONATING_WRAPPERS:
                idx = _donated_indices(node.value)
                if idx:
                    donors[node.targets[0].id] = idx
            else:
                # interprocedural donors: a name bound to the RESULT of a
                # step factory (`step = make_dp_train_step(...)` returns
                # jit(..., donate_argnums=(0,))) donates at that factory's
                # recorded positions — the live cli/train.py shape
                from .summaries import donated_caller_positions

                scope = self._cg.enclosing_scope(src, node)
                t = self._cg.resolve_expr(src, node.value, scope)
                if t is not None and t.kind == "jit":
                    idx = donated_caller_positions(project, t)
                    if idx:
                        donors[node.targets[0].id] = idx
        out: dict[tuple, Finding] = {}
        scopes: list[ast.AST] = [src.tree]
        scopes += [
            n for n in src.nodes if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            self._scope = scope if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)) else None
            self._src = src
            self._block(list(scope.body), {}, donors, src, out)
        return list(out.values())

    # -- statement walk (linear flow; branches merged by union) --------------

    def _block(self, stmts, donated: dict[str, tuple[str, int]], donors, src, out):
        for st in stmts:
            self._stmt(st, donated, donors, src, out)

    def _stmt(self, st, donated, donors, src, out):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate scope (closures over donated vars are out of scope)
        if isinstance(st, ast.If):
            self._expr(st.test, donated, donors, src, out)
            b1, b2 = dict(donated), dict(donated)
            self._block(st.body, b1, donors, src, out)
            self._block(st.orelse, b2, donors, src, out)
            donated.clear()
            donated.update({**b1, **b2})  # union: donated on ANY path is a hazard
        elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(st, ast.While):
                self._expr(st.test, donated, donors, src, out)
            else:
                self._expr(st.iter, donated, donors, src, out)
                self._clear_targets(st.target, donated)
            # two passes: a donation at the bottom of the body reaches a read
            # at the top of the next iteration (findings dedupe by location)
            for _ in range(2):
                self._block(st.body, donated, donors, src, out)
            self._block(st.orelse, donated, donors, src, out)
        elif isinstance(st, ast.Try):
            branches = []
            for block in (st.body, *[h.body for h in st.handlers], st.orelse):
                b = dict(donated)
                self._block(block, b, donors, src, out)
                branches.append(b)
            donated.clear()
            for b in branches:
                donated.update(b)
            self._block(st.finalbody, donated, donors, src, out)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr, donated, donors, src, out)
                if item.optional_vars is not None:
                    self._clear_targets(item.optional_vars, donated)
            self._block(st.body, donated, donors, src, out)
        elif isinstance(st, ast.Assign):
            self._expr(st.value, donated, donors, src, out)
            for t in st.targets:
                self._clear_targets(t, donated)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            if st.value is not None:
                self._expr(st.value, donated, donors, src, out)
            if isinstance(st, ast.AugAssign):
                # x += ... both reads and writes x
                self._expr(st.target, donated, donors, src, out)
            self._clear_targets(st.target, donated)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                self._clear_targets(t, donated)
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._expr(child, donated, donors, src, out)

    def _clear_targets(self, target, donated):
        if isinstance(target, ast.Name):
            donated.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._clear_targets(el, donated)
        elif isinstance(target, ast.Starred):
            self._clear_targets(target.value, donated)

    # -- expression walk -----------------------------------------------------

    def _expr(self, expr, donated, donors, src, out):
        if expr is None or isinstance(expr, ast.Lambda):
            return
        if isinstance(expr, ast.Name) and isinstance(expr.ctx, ast.Load):
            hit = donated.get(expr.id)
            if hit is not None:
                fn, line = hit
                f = Finding(
                    src.path, expr.lineno, expr.col_offset, self.id,
                    f"'{expr.id}' read after being donated to '{fn}' (line {line}, "
                    "jit donate_argnums): the buffer is deleted after dispatch — "
                    "rebind the variable to the call's result or drop the donation",
                )
                out.setdefault((f.line, f.col, expr.id), f)
            return
        # children in evaluation order; a donating call marks its donated
        # args only AFTER its own arguments were read (passing x twice in the
        # same call is simultaneous, not read-after-donate)
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._expr(child, donated, donors, src, out)
            elif isinstance(child, ast.keyword):
                self._expr(child.value, donated, donors, src, out)
        if isinstance(expr, ast.Call):
            idx: tuple[int, ...] = ()
            label = ""
            if isinstance(expr.func, ast.Name):
                idx = donors.get(expr.func.id, ())
                label = expr.func.id
            if not idx:
                # attribute calls (`trainer.train_step(ts, b)`) and calls to
                # functions whose SUMMARY donates (a wrapper forwarding to a
                # donating jit) resolve through the call graph; opaque calls
                # stay skipped — never guess a donation
                from .summaries import donated_caller_positions

                t = self._cg.resolve_call(self._src, expr, self._scope)
                idx = donated_caller_positions(self._project, t)
                label = _call_label(expr.func)
            for i in idx:
                if i < len(expr.args) and isinstance(expr.args[i], ast.Name):
                    donated[expr.args[i].id] = (label, expr.lineno)
