"""Per-function dataflow summaries over the call graph.

For every def in the project (top-level, method, nested closure) a
:class:`FunctionSummary` records the facts the interprocedural rules need:

- ``key_params`` — parameters consumed as PRNG keys: passed as the first
  positional argument of any ``jax.random.*`` call (``split``/``fold_in``
  included: two callees splitting the SAME key derive the same streams), or
  passed whole to a resolved callee whose matching parameter is
  key-consuming (transitive, via fixpoint). YAMT010's ground truth.
- ``donated_params`` — positional parameter indices whose buffer is donated
  when the function runs: the parameter is passed at a donated position of a
  ``jit(..., donate_argnums=...)`` callable or of a callee that itself
  donates. YAMT008's cross-call ground truth.
- ``returns`` — the resolved Target of the function's return value when it
  is a callable we can model: a jit wrapper (``return jax.jit(fn,
  donate_argnums=(0,))`` — the cli/train.py step-factory shape) or a local
  def (``return step_fn`` — the make_train_step shape). This is what lets
  ``step = make_dp_train_step(...)`` act as a donating function at its call
  sites two modules away.

The fixpoint iterates until no summary changes (bounded); resolution that
cannot be decided stays absent — over-approximation is only ever toward
"don't flag".
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .core import qualified_name
from .callgraph import Target
from .symbols import FunctionInfo

# jax.random functions whose first argument is NOT a key
_NON_KEY_FIRST_ARG = {"PRNGKey", "key", "wrap_key_data"}

_MAX_ROUNDS = 12


@dataclasses.dataclass
class FunctionSummary:
    fi: FunctionInfo
    key_params: set[str] = dataclasses.field(default_factory=set)
    donated_params: set[int] = dataclasses.field(default_factory=set)
    returns: Optional[Target] = None

    def caller_donated_positions(self, bound: bool) -> tuple[int, ...]:
        """Donated positions as the CALLER sees them (``self`` already bound
        for instance-method calls)."""
        if bound:
            return tuple(sorted(i - 1 for i in self.donated_params if i >= 1))
        return tuple(sorted(self.donated_params))

    def param_at(self, index: int, bound: bool) -> Optional[str]:
        pos = self.fi.pos_params[1:] if bound else self.fi.pos_params
        return pos[index] if 0 <= index < len(pos) else None


def summary_for_target(project, target: Optional[Target]) -> Optional[FunctionSummary]:
    """The FunctionSummary behind a resolved call target (unwrapping one
    jit layer), or None for anything opaque."""
    if target is None:
        return None
    if target.kind == "jit" and target.inner is not None:
        target = target.inner
    if target.kind != "function" or target.func is None:
        return None
    return project.summaries.get(target.func.qualname)


def donated_caller_positions(project, target: Optional[Target]) -> tuple[int, ...]:
    """Caller-side donated positions of a call to ``target`` ((), if none)."""
    if target is None:
        return ()
    if target.kind == "jit":
        if target.donate:
            return target.donate
        return ()
    if target.kind == "function":
        s = summary_for_target(project, target)
        if s is not None:
            return s.caller_donated_positions(target.bound)
    return ()


def compute(project, out: dict[str, FunctionSummary]) -> None:
    """Fill ``out`` (qualname -> summary) to fixpoint. ``out`` is installed
    on the project BEFORE this runs, so the call graph's returns-resolution
    sees partial results and sharpens round over round."""
    symbols = project.symbols
    cg = project.callgraph
    infos = list(symbols.by_node.values())
    for fi in infos:
        out[fi.qualname] = FunctionSummary(fi)

    # per-function call-site lists are STATIC across fixpoint rounds: the
    # walk, the qualified-name lookup, and the enclosing scope never change
    # — only call-target resolution sharpens round over round. Precomputing
    # them once keeps later rounds to pure resolution work.
    sites: dict[str, list] = {}
    for fi in infos:
        src = fi.module.src
        aliases = src.aliases
        sites[fi.qualname] = [
            (node, qualified_name(node.func, aliases), cg.enclosing_scope(src, node))
            for node in src.subtree(fi.node)
            if isinstance(node, ast.Call)
        ]

    for _ in range(_MAX_ROUNDS):
        changed = False
        for fi in infos:
            s = out[fi.qualname]
            changed |= _scan_function(project, cg, fi, s, sites[fi.qualname])
        if not changed:
            break


def _scan_function(project, cg, fi: FunctionInfo, s: FunctionSummary, sites) -> bool:
    src = fi.module.src
    params = fi.all_params
    pos = fi.pos_params
    changed = False

    for node, q, scope in sites:
        if q and q.startswith("jax.random.") and q.rsplit(".", 1)[-1] not in _NON_KEY_FIRST_ARG:
            if node.args and isinstance(node.args[0], ast.Name) and node.args[0].id in params:
                if node.args[0].id not in s.key_params:
                    s.key_params.add(node.args[0].id)
                    changed = True
            continue
        target = cg.resolve_call(src, node, scope)
        if target is None:
            continue
        callee = summary_for_target(project, target)
        if callee is not None:
            bound = target.kind == "function" and target.bound
            for i, arg in enumerate(node.args):
                if not (isinstance(arg, ast.Name) and arg.id in params):
                    continue
                pname = callee.param_at(i, bound)
                if pname is not None and pname in callee.key_params and arg.id not in s.key_params:
                    s.key_params.add(arg.id)
                    changed = True
            for kw in node.keywords:
                if (
                    kw.arg is not None
                    and isinstance(kw.value, ast.Name)
                    and kw.value.id in params
                    and kw.arg in callee.key_params
                    and kw.value.id not in s.key_params
                ):
                    s.key_params.add(kw.value.id)
                    changed = True
        for d in donated_caller_positions(project, target):
            if d < len(node.args) and isinstance(node.args[d], ast.Name):
                name = node.args[d].id
                if name in pos:
                    idx = pos.index(name)
                    if idx not in s.donated_params:
                        s.donated_params.add(idx)
                        changed = True

    if s.returns is None:
        ret = _returned_callable(cg, fi)
        if ret is not None:
            s.returns = ret
            changed = True
    return changed


def _returned_callable(cg, fi: FunctionInfo) -> Optional[Target]:
    """First return value (own body only, not nested defs) that resolves to
    a jit wrapper, a project function, or a project-class instance (so
    method calls on a factory's result — ``predict_async(x).result()`` —
    resolve through the returned class)."""
    src = fi.module.src
    stack = list(fi.node.body)
    while stack:
        st = stack.pop(0)
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        if isinstance(st, ast.Return) and st.value is not None:
            t = cg.resolve_expr(src, st.value, fi.node)
            if t is not None and t.kind in ("jit", "function", "instance"):
                return t
            continue
        for block in ("body", "orelse", "finalbody"):
            stack.extend(getattr(st, block, []))
        for h in getattr(st, "handlers", []):
            stack.extend(h.body)
    return None
