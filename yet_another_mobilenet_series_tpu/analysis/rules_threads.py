"""YAMT011 — unguarded thread-target functions in package code.

A worker thread that dies on an unhandled exception dies SILENTLY: Python
prints a traceback to stderr (if anyone is watching) and the thread is gone,
while everything that depended on it — queued futures, the in-flight window,
the heartbeat the watchdog waits for — hangs forever. For the serving stack
this is the worst failure mode there is: a crashed collect/completion/accept
thread turns every client call into an unbounded wait (the motivating bug
class behind serve/batcher.py's ``_crash_guard`` and the drain timeout).

The rule: every function handed to ``threading.Thread(target=...)`` in
package code must carry a TOP-LEVEL exception guard — after the docstring
and trivial setup statements (assignments, imports, ``global``/``nonlocal``,
``pass``), the function's work must live inside a ``try:`` that has at least
one ``except`` handler. ``try/finally`` alone does not count: the exception
still escapes and kills the thread. What the handler DOES is the author's
policy (fail live futures, count ``serve.thread_crashes``, write stderr) —
the rule only insists the death is handled, not how.

Scope and resolution, matching the sibling rules' pragmatics:

- **package code only** (a directory holding ``__init__.py``) — standalone
  scripts and tests exempt, like YAMT007;
- targets resolved within the file: a plain name binds to the (nearest)
  ``def`` with that name in the module (including nested defs — the
  closure-worker idiom), ``self.<method>`` binds to the method on the
  enclosing class (or any class in the file defining it — the
  ``_start_threads`` override idiom);
- a ``lambda`` target is flagged outright (a lambda cannot contain a
  guard);
- targets the file cannot resolve (callables from other modules, factory
  results, ``functools.partial``) degrade to silence, not noise.

Guarded-delegation counts: a one-statement body that is itself the guard
(``try: self._loop_inner() except Exception: ...``) is the sanctioned
wrapper shape.
"""

from __future__ import annotations

import ast
import os

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

# setup statements allowed before/around the guarded try at function top
# level — bindings and declarations, not control flow doing real work
_SETUP_STMTS = (
    ast.Assign,
    ast.AnnAssign,
    ast.AugAssign,
    ast.Import,
    ast.ImportFrom,
    ast.Global,
    ast.Nonlocal,
    ast.Pass,
)


def _body_sans_docstring(fn: ast.FunctionDef) -> list[ast.stmt]:
    body = list(fn.body)
    if body and isinstance(body[0], ast.Expr) and isinstance(body[0].value, ast.Constant) \
            and isinstance(body[0].value.value, str):
        body = body[1:]
    return body


def _is_guarded(fn: ast.FunctionDef) -> bool:
    """Top-level guard check: every non-setup statement is a try-with-except
    (finally-only does not stop the exception), and at least one exists."""
    body = _body_sans_docstring(fn)
    guarded_tries = 0
    for st in body:
        if isinstance(st, ast.Try):
            if not st.handlers:
                return False  # try/finally alone: the exception still escapes
            guarded_tries += 1
        elif not isinstance(st, _SETUP_STMTS):
            return False
    return guarded_tries > 0


class _DefIndex:
    """Function/method definitions in one module, for target resolution."""

    def __init__(self, src: SourceFile):
        self.by_name: dict[str, list[ast.FunctionDef]] = {}
        self.methods: dict[tuple[str, str], ast.FunctionDef] = {}
        self.enclosing_class: dict[int, str] = {}
        for node in src.nodes:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.methods[(node.name, sub.name)] = sub
                for sub in src.subtree(node):
                    self.enclosing_class[id(sub)] = node.name
            if isinstance(node, ast.FunctionDef):
                self.by_name.setdefault(node.name, []).append(node)

    def resolve(self, target: ast.expr, call: ast.Call) -> list[ast.FunctionDef]:
        """Candidate defs for a Thread target expression; [] = opaque."""
        if isinstance(target, ast.Name):
            return self.by_name.get(target.id, [])
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            # the enclosing class first (the _start_threads shape), then any
            # class in the file defining that method (subclass overrides)
            cls = self.enclosing_class.get(id(call))
            hit = self.methods.get((cls, target.attr)) if cls else None
            if hit is not None:
                return [hit]
            return [m for (c, name), m in self.methods.items() if name == target.attr]
        return []


@register
class UnguardedThreadTarget(Rule):
    id = "YAMT011"
    name = "unguarded-thread-target"
    description = (
        "a threading.Thread target function in package code without a top-level "
        "try/except guard: an unhandled exception kills the thread SILENTLY and "
        "hangs everything waiting on it (futures, windows, heartbeats) — wrap the "
        "body in a guard that fails dependents loudly"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        # package code only: a dir with __init__.py (scripts/tests exempt)
        if not os.path.exists(os.path.join(os.path.dirname(src.path), "__init__.py")):
            return []
        index = None
        findings: list[Finding] = []
        flagged: set[int] = set()  # one finding per target def, however many Thread()s
        for node in src.nodes:
            if not isinstance(node, ast.Call):
                continue
            q = qualified_name(node.func, src.aliases)
            if q != "threading.Thread":
                continue
            target = next((kw.value for kw in node.keywords if kw.arg == "target"), None)
            if target is None:
                continue
            if isinstance(target, ast.Lambda):
                findings.append(Finding(
                    src.path, target.lineno, target.col_offset, self.id,
                    "lambda thread target cannot carry an exception guard: "
                    "use a def with a top-level try/except",
                ))
                continue
            if index is None:
                index = _DefIndex(src)
            for fn in index.resolve(target, node):
                if id(fn) in flagged or _is_guarded(fn):
                    continue
                flagged.add(id(fn))
                findings.append(Finding(
                    src.path, fn.lineno, fn.col_offset, self.id,
                    f"thread target '{fn.name}' has no top-level try/except guard: "
                    "an unhandled exception kills the thread silently and hangs "
                    "its dependents (try/finally alone still lets it escape)",
                ))
        return findings
