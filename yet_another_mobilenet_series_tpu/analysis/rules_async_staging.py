"""YAMT014 — host buffer mutated under an async ``jax.device_put`` transfer.

``jax.device_put`` may return BEFORE the device has read the host buffer
(that is the point: the H2D copy overlaps compute). Rewriting the buffer
after handing it to ``device_put`` with no intervening sync therefore races
the transfer: on backends where the copy really is asynchronous the device
reads TORN data — silently, and only under load, which is the worst kind of
serving bug. The live hazard is exactly the serving engine's staging-slot
reuse (serve/engine.py): the sync ``jnp.asarray`` copy that used to make
reuse safe was replaced by async ``device_put``, and the invariant moved
into an explicit fence — a slot's buffer is rewritten only after its last
transfer is KNOWN complete (``_SlotPool.acquire`` blocks on
``jax.block_until_ready`` of the consuming dispatch's outputs). This rule
pins that discipline wherever the idiom is written inline.

A buffer name passed positionally to ``jax.device_put`` is *in transfer*
until a **ready check** — a ``jax.block_until_ready(...)`` call or any
``.block_until_ready()`` method call (a global sync point: every pending
transfer is done once ANYTHING later-enqueued is ready) — or until the name
is rebound or deleted. While in transfer, a mutation of the buffer flags:

- subscript stores (``buf[:n] = rows``, ``buf[i] += x``),
- in-place augmented assignment (``buf += x`` mutates numpy arrays),
- mutating method calls (``buf.fill/put/sort/resize/partition``),
- ``np.copyto(buf, ...)``.

Flow handling is deliberately simple — statements are scanned in source
order within one function (nested defs/lambdas are their own scope, a
caller's sync is invisible), and loop bodies are walked twice so a transfer
at the bottom of an iteration reaches a rewrite at the top of the next (the
canonical staging-loop shape). Branches are not forked: a ready check on
any earlier line is credited, so the guarded first-iteration idiom
(``if fence is not None: jax.block_until_ready(fence)``) stays clean. The
split producer/consumer shape — mutate in one function, transfer in
another, fence waited in a third (the engine's slot pool) — is out of a
function-local rule's sight by design: the pool class IS the sanctioned
carrier of that invariant.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, Rule, SourceFile, qualified_name, register

_MUTATING_METHODS = {"fill", "put", "sort", "resize", "partition", "setfield"}


def _iter_nodes(node: ast.AST):
    """Depth-first pre-order traversal (≈ source order) that does NOT
    descend into nested scopes — their buffers are their own problem."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        yield child
        yield from _iter_nodes(child)


def _sub_name(target: ast.expr) -> str | None:
    """``buf`` of a ``buf[...]`` store target."""
    if isinstance(target, ast.Subscript) and isinstance(target.value, ast.Name):
        return target.value.id
    return None


class _Scanner:
    """Linear event interpreter for one scope: transfer marks, ready-check
    clears, mutation findings (deduped by location for the double loop
    pass)."""

    def __init__(self, rule: "AsyncStagingMutation", src: SourceFile):
        self.rule = rule
        self.src = src
        self.marks: dict[str, int] = {}  # buffer name -> device_put line
        self.out: dict[tuple, Finding] = {}

    def run(self, stmts) -> None:
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            self._exprs(st.test if isinstance(st, ast.While) else st.iter)
            # two passes: a transfer at the bottom of the body reaches a
            # rewrite at the top of the next iteration
            for _ in range(2):
                for s in st.body:
                    self._stmt(s)
            for s in st.orelse:
                self._stmt(s)
            return
        if isinstance(st, (ast.If, ast.Try, ast.With, ast.AsyncWith)):
            # linear, not forked: bodies scanned in source order (a ready
            # check in an earlier branch is credited — see module docstring)
            if isinstance(st, ast.If):
                self._exprs(st.test)
                blocks = [st.body, st.orelse]
            elif isinstance(st, ast.Try):
                blocks = [st.body, *[h.body for h in st.handlers], st.orelse, st.finalbody]
            else:
                for item in st.items:
                    self._exprs(item.context_expr)
                blocks = [st.body]
            for block in blocks:
                for s in block:
                    self._stmt(s)
            return
        if isinstance(st, ast.Assign):
            self._exprs(st.value)
            for t in st.targets:
                self._store(t)
            return
        if isinstance(st, ast.AugAssign):
            self._exprs(st.value)
            if isinstance(st.target, ast.Name):
                # numpy `buf += x` mutates in place, then rebinds the name
                self._mutate(st.target.id, st.target)
                self.marks.pop(st.target.id, None)
            else:
                self._store(st.target)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    self.marks.pop(t.id, None)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._exprs(child)

    def _store(self, target: ast.expr) -> None:
        if isinstance(target, ast.Name):
            self.marks.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._store(el)
        elif isinstance(target, ast.Starred):
            self._store(target.value)
        else:
            name = _sub_name(target)
            if name is not None:
                self._mutate(name, target)

    def _exprs(self, expr: ast.expr | None) -> None:
        if expr is None:
            return
        for node in [expr, *_iter_nodes(expr)]:
            if not isinstance(node, ast.Call):
                continue
            q = qualified_name(node.func, self.src.aliases) or ""
            if q == "jax.device_put":
                if node.args and isinstance(node.args[0], ast.Name):
                    self.marks[node.args[0].id] = node.lineno
            elif q == "jax.block_until_ready" or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                # a global sync point: everything enqueued before it —
                # including every pending H2D transfer — is complete
                self.marks.clear()
            elif q in ("np.copyto", "numpy.copyto"):
                if node.args and isinstance(node.args[0], ast.Name):
                    self._mutate(node.args[0].id, node.args[0])
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_METHODS
                and isinstance(node.func.value, ast.Name)
            ):
                self._mutate(node.func.value.id, node)

    def _mutate(self, name: str, node: ast.AST) -> None:
        line = self.marks.get(name)
        if line is None:
            return
        f = Finding(
            self.src.path, node.lineno, node.col_offset, self.rule.id,
            f"'{name}' mutated after being passed to jax.device_put (line {line}) "
            "with no intervening ready check: the async H2D transfer may still "
            "be reading the buffer — wait on a fence (jax.block_until_ready of "
            "the transfer or its consumer's outputs) before rewriting it",
        )
        self.out.setdefault((f.line, f.col, name), f)


@register
class AsyncStagingMutation(Rule):
    id = "YAMT014"
    name = "async-staging-mutation"
    description = (
        "host buffer mutated after being passed to an async jax.device_put with "
        "no intervening sync/ready check: the transfer may still be reading the "
        "buffer, so the device can observe torn data (serve/engine.py's slot "
        "fence is the sanctioned idiom)"
    )

    def check_file(self, src: SourceFile, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        scopes: list[ast.AST] = [src.tree]
        scopes += [
            n for n in src.nodes
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            scanner = _Scanner(self, src)
            scanner.run(scope.body)
            findings.extend(scanner.out.values())
        return findings
