"""Checkpointing: Orbax manager with network-spec sidecar."""

from .manager import CheckpointManager

__all__ = ["CheckpointManager"]
