"""Checkpoint/resume via Orbax + architecture-spec sidecar (reference:
torch.save dict {epoch, model, EMA, optimizer, lr step, live AtomNAS spec},
save-on-master-only, SURVEY.md §3.5 / §5).

The critical ordering subtlety reproduced here: on AtomNAS resume the *live
network spec* must be restored first so the model is rebuilt at the pruned
shape, and only then can the weight trees load (SURVEY.md §3.5). The spec
rides in the same Orbax step directory as a JSON item next to the pytree.

Orbax gives async saves (preemption loses minutes, not epochs — SURVEY.md §5
failure-detection plan) and multi-host coordination for free.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..models.serialize import network_from_dict, network_to_dict
from ..models.specs import Network
from ..obs import trace as obs_trace
from ..obs.registry import get_registry


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3, async_save: bool = True, barrier_prefix: str | None = None):
        """barrier_prefix namespaces Orbax's cross-host sync barriers.

        Orbax barrier keys are global per process (e.g.
        ``_async_write_complete.<step>``): when two managers save the SAME
        step concurrently — exactly what happens when the periodic manager
        and the best-checkpoint manager both fire on the final eval — the
        second multi-host barrier dies with FAILED_PRECONDITION "already
        ongoing" and takes the whole distributed job down. Single-host runs
        never hit this (no distributed barrier), so every extra manager
        MUST pass a distinct prefix (caught by tests/test_multiproc.py)."""
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
                create=True,
                multiprocessing_options=ocp.checkpoint_manager.MultiprocessingOptions(
                    barrier_sync_key_prefix=barrier_prefix
                ),
            ),
        )

    def save(self, step: int, net: Network, train_state, extra: dict[str, Any] | None = None):
        """Saves the TrainState pytree + live network spec (+ small JSON extras
        like epoch/masks metadata)."""
        from ..train.steps import train_state_to_dict

        tree = train_state_to_dict(train_state)
        meta = {"network": network_to_dict(net), "extra": extra or {}}
        # the span covers only the host-side enqueue of the (async) save;
        # the barrier cost shows up in ckpt/wait and the wait histogram
        with obs_trace.get_tracer().span("ckpt/save", "ckpt", step=int(step)):
            self._mgr.save(
                step,
                args=ocp.args.Composite(
                    tree=ocp.args.StandardSave(tree),
                    meta=ocp.args.JsonSave(meta),
                ),
            )
        get_registry().counter("ckpt.saves").inc()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_spec(self, step: int | None = None):
        """Phase 1 of resume: returns (step, net, extra) with the network
        rebuilt from the JSON sidecar BEFORE any weights are read — the
        pruned-shape-first ordering of SURVEY.md §3.5. The caller then builds
        the optimizer/TrainState skeleton at this shape and passes its
        abstract tree to restore_tree."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        with obs_trace.get_tracer().span("ckpt/restore_spec", "ckpt", step=int(step)):
            meta = self._mgr.restore(step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))["meta"]
        return step, network_from_dict(meta["network"]), meta["extra"]

    def restore_tree(self, step: int, abstract_tree=None):
        """Phase 2: restore the pytree against an abstract target so optax
        NamedTuple states and dtypes round-trip exactly. ``None`` restores
        as-saved (plain nested dicts of host arrays) — the serving export
        path (serve/export.py) reads weights without rebuilding an optimizer
        skeleton."""
        with obs_trace.get_tracer().span("ckpt/restore_tree", "ckpt", step=int(step)):
            restore_args = ocp.args.StandardRestore(abstract_tree) if abstract_tree is not None else ocp.args.StandardRestore()
            tree = self._mgr.restore(
                step, args=ocp.args.Composite(tree=restore_args)
            )["tree"]
        get_registry().counter("ckpt.restores").inc()
        return tree

    def wait(self):
        # the multi-host barrier wait the registry was built to surface: a
        # slow/contended filesystem shows up here, not in step time
        t0 = time.perf_counter()
        with obs_trace.get_tracer().span("ckpt/wait", "ckpt"):
            self._mgr.wait_until_finished()
        get_registry().histogram("ckpt.wait_seconds").observe(time.perf_counter() - t0)

    def close(self):
        self._mgr.close()
