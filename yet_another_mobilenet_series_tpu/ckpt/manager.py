"""Checkpoint/resume via Orbax + architecture-spec sidecar (reference:
torch.save dict {epoch, model, EMA, optimizer, lr step, live AtomNAS spec},
save-on-master-only, SURVEY.md §3.5 / §5).

The critical ordering subtlety reproduced here: on AtomNAS resume the *live
network spec* must be restored first so the model is rebuilt at the pruned
shape, and only then can the weight trees load (SURVEY.md §3.5). The spec
rides in the same Orbax step directory as a JSON item next to the pytree.

Orbax gives async saves (preemption loses minutes, not epochs — SURVEY.md §5
failure-detection plan) and multi-host coordination for free.

Crash consistency (the robustness PR): every save also records a per-item
sha256 digest in ``digests.json`` at the manager root, and every RESUME
restore (abstract-targeted) recomputes and compares — a half-written or
bit-rotted item that Orbax's own storage checks miss raises
:class:`CheckpointCorrupt` instead of silently resuming from garbage. The
digest file lives OUTSIDE the step dirs so Orbax's max_to_keep garbage
collection never races it; entries for collected steps are pruned at the
next save. ``all_steps()``/``tree_keys()`` feed cli/train.py's fallback
restore: when the latest checkpoint is unusable (corrupt meta JSON,
truncated tree item, digest mismatch) resume walks back step by step
instead of crashing.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..models.serialize import network_from_dict, network_to_dict
from ..models.specs import Network
from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..utils.logging import emit

DIGEST_NAME = "digests.json"


class CheckpointCorrupt(RuntimeError):
    """Restored checkpoint bytes do not match the per-item digests recorded
    at save time — a half-written or corrupted item. The resume path treats
    this exactly like an Orbax read error: fall back to an older step."""


def _item_digests(tree: dict) -> dict[str, str]:
    """Per-top-level-item sha256 over every leaf's (dtype, shape, bytes) in
    flatten order. Items whose subtree holds no array leaves (None fields —
    EMA off, rho_mult without pruning) are omitted: there are no bytes to
    protect and the save/restore structures agree by construction."""
    out: dict[str, str] = {}
    for key in sorted(tree):
        leaves = jax.tree_util.tree_leaves(tree[key])
        if not leaves:
            continue
        h = hashlib.sha256()
        for leaf in leaves:
            a = np.asarray(leaf)
            h.update(str(a.dtype).encode())
            h.update(repr(a.shape).encode())
            h.update(a.tobytes())
        out[key] = h.hexdigest()
    return out


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3, async_save: bool = True,
                 barrier_prefix: str | None = None, integrity: bool = True):
        """barrier_prefix namespaces Orbax's cross-host sync barriers.

        Orbax barrier keys are global per process (e.g.
        ``_async_write_complete.<step>``): when two managers save the SAME
        step concurrently — exactly what happens when the periodic manager
        and the best-checkpoint manager both fire on the final eval — the
        second multi-host barrier dies with FAILED_PRECONDITION "already
        ongoing" and takes the whole distributed job down. Single-host runs
        never hit this (no distributed barrier), so every extra manager
        MUST pass a distinct prefix (caught by tests/test_multiproc.py).

        integrity=False skips digest bookkeeping (benches that checkpoint in
        a tight loop); resume then behaves exactly as before this landed."""
        self._dir = directory
        self._integrity = integrity
        self._max_to_keep = max_to_keep
        self._digest_warned = False
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
                create=True,
                multiprocessing_options=ocp.checkpoint_manager.MultiprocessingOptions(
                    barrier_sync_key_prefix=barrier_prefix
                ),
            ),
        )

    def save(self, step: int, net: Network, train_state, extra: dict[str, Any] | None = None):
        """Saves the TrainState pytree + live network spec (+ small JSON extras
        like epoch/masks metadata)."""
        from ..train.steps import train_state_to_dict

        tree = train_state_to_dict(train_state)
        meta = {"network": network_to_dict(net), "extra": extra or {}}
        # the span covers only the host-side enqueue of the (async) save;
        # the barrier cost shows up in ckpt/wait and the wait histogram
        with obs_trace.get_tracer().span("ckpt/save", "ckpt", step=int(step)):
            self._mgr.save(
                step,
                args=ocp.args.Composite(
                    tree=ocp.args.StandardSave(tree),
                    meta=ocp.args.JsonSave(meta),
                ),
            )
        if self._integrity and jax.process_index() == 0:
            # digests are computed from the live host tree BEFORE the async
            # write lands, so a torn write can never produce matching bytes;
            # coordinator-only like the JSON sidecars orbax itself writes
            self._record_digests(int(step), _item_digests(tree))
        get_registry().counter("ckpt.saves").inc()

    # -- digest sidecar ------------------------------------------------------

    def _load_digests(self) -> dict:
        try:
            with open(os.path.join(self._dir, DIGEST_NAME)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _record_digests(self, step: int, digests: dict[str, str]) -> None:
        index = self._load_digests()
        index[str(step)] = digests
        # prune entries for steps Orbax already garbage-collected (keep a
        # max_to_keep-sized margin: the collection is async)
        live = {str(s) for s in self._mgr.all_steps()} | {str(step)}
        keep = {k: v for k, v in index.items() if k in live}
        tmp = os.path.join(self._dir, f"{DIGEST_NAME}.tmp.{os.getpid()}")
        try:
            with open(tmp, "w") as f:
                json.dump(keep, f, indent=0, sort_keys=True)
            os.replace(tmp, os.path.join(self._dir, DIGEST_NAME))
        except OSError as e:
            # a read-only or full checkpoint dir degrades integrity
            # bookkeeping, not the save itself — but say so, once
            if not self._digest_warned:
                self._digest_warned = True
                emit(f"[ckpt] WARNING: could not write {DIGEST_NAME} "
                     f"({type(e).__name__}: {e}); restore integrity "
                     "verification is disabled for this run")
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _verify(self, step: int, tree: dict) -> None:
        expected = self._load_digests().get(str(step))
        if not expected:
            return  # pre-digest checkpoint (or sidecar lost): nothing to judge
        actual = _item_digests(tree)
        bad = sorted(k for k, v in actual.items() if k in expected and expected[k] != v)
        if bad:
            get_registry().counter("ckpt.integrity_failures").inc()
            raise CheckpointCorrupt(
                f"step {step}: restored item(s) {bad} do not match the digests "
                f"recorded at save time (half-written or corrupted checkpoint)"
            )

    # -- queries -------------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        """Available checkpoint steps, NEWEST FIRST — the fallback-restore
        candidate order (cli/train.py _restore)."""
        return sorted((int(s) for s in self._mgr.all_steps()), reverse=True)

    def tree_keys(self, step: int) -> set[str] | None:
        """Top-level item names of the saved tree, from Orbax metadata only
        (no array reads). None when the metadata itself is unreadable.

        This is what lets the resume path tell a LEGACY layout (a field
        genuinely absent from the save, e.g. pre-rho_mult checkpoints) from
        corruption of a field that IS on disk — the distinction the old bare
        ``except Exception`` retry erased."""
        try:
            md = self._mgr.item_metadata(step)["tree"]
            return set(md.keys())
        except Exception as e:  # noqa: BLE001 — metadata loss is itself corruption
            emit(f"[ckpt] step {step}: tree metadata unreadable "
                 f"({type(e).__name__}: {e})")
            return None

    # -- restore -------------------------------------------------------------

    def restore_spec(self, step: int | None = None):
        """Phase 1 of resume: returns (step, net, extra) with the network
        rebuilt from the JSON sidecar BEFORE any weights are read — the
        pruned-shape-first ordering of SURVEY.md §3.5. The caller then builds
        the optimizer/TrainState skeleton at this shape and passes its
        abstract tree to restore_tree."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        with obs_trace.get_tracer().span("ckpt/restore_spec", "ckpt", step=int(step)):
            meta = self._mgr.restore(step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))["meta"]
        return step, network_from_dict(meta["network"]), meta["extra"]

    def restore_tree(self, step: int, abstract_tree=None):
        """Phase 2: restore the pytree against an abstract target so optax
        NamedTuple states and dtypes round-trip exactly. ``None`` restores
        as-saved (plain nested dicts of host arrays) — the serving export
        path (serve/export.py) reads weights without rebuilding an optimizer
        skeleton.

        Abstract-targeted restores (the RESUME path) are digest-verified
        against the save-time sidecar; a mismatch raises
        :class:`CheckpointCorrupt`. The as-saved path skips verification:
        without the abstract target Orbax rebuilds optax containers as plain
        dicts, which changes leaf order, and export reads are not the
        crash-consistency surface."""
        with obs_trace.get_tracer().span("ckpt/restore_tree", "ckpt", step=int(step)):
            restore_args = ocp.args.StandardRestore(abstract_tree) if abstract_tree is not None else ocp.args.StandardRestore()
            tree = self._mgr.restore(
                step, args=ocp.args.Composite(tree=restore_args)
            )["tree"]
        if abstract_tree is not None and self._integrity:
            self._verify(int(step), tree)
        get_registry().counter("ckpt.restores").inc()
        return tree

    def wait(self):
        # the multi-host barrier wait the registry was built to surface: a
        # slow/contended filesystem shows up here, not in step time
        t0 = time.perf_counter()
        with obs_trace.get_tracer().span("ckpt/wait", "ckpt"):
            self._mgr.wait_until_finished()
        get_registry().histogram("ckpt.wait_seconds").observe(time.perf_counter() - t0)

    def close(self):
        self._mgr.close()
