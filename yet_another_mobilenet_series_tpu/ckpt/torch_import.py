"""Torch checkpoint importer: torchvision-layout MobileNetV2 ``state_dict`` →
our ``(params, state)`` pytrees (SURVEY.md §3.3, acceptance config #1 — eval a
real pretrained MobileNetV2; VERDICT round-1 item #3).

The reference repo's own checkpoints are torch ``state_dict`` dicts; with the
reference mount empty, the public torchvision MobileNetV2 layout is the
importable format (the weights themselves are interchangeable — same
architecture). Layout handled:

    features.0.0.weight                  stem conv            (OIHW)
    features.0.1.{weight,bias,running_mean,running_var}       stem BN
    features.i.conv.0.0 / 0.1            expand conv/BN       (t>1 blocks)
    features.i.conv.{1.0,1.1}            depthwise conv/BN    (t>1 blocks)
    features.i.conv.{0.0,0.1}            depthwise conv/BN    (t=1 block)
    features.i.conv.{2,3} (or {1,2})     project conv/BN
    features.18.0 / 18.1                 head conv/BN
    classifier.1.{weight,bias}           classifier Linear

Transforms: conv OIHW → HWIO ``transpose(2,3,1,0)`` (depthwise (C,1,k,k) →
(k,k,1,C) under the same transpose), Linear (out,in) → (in,out), BN
weight/bias/running_mean/running_var → gamma/beta/mean/var;
``num_batches_tracked`` is dropped.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from ..models.specs import Network


def _np(t) -> np.ndarray:
    """torch.Tensor | array-like -> float32 numpy (no torch import needed
    unless the input actually is a tensor)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _conv_w(t) -> np.ndarray:
    return np.ascontiguousarray(_np(t).transpose(2, 3, 1, 0))  # OIHW -> HWIO


class CheckpointImportError(ValueError):
    pass


class _SD:
    """state_dict view that tracks consumption so leftovers are an error."""

    def __init__(self, sd: Mapping[str, Any]):
        self.sd = dict(sd)
        self.used: set[str] = set()

    def take(self, key: str) -> np.ndarray:
        if key not in self.sd:
            raise CheckpointImportError(f"missing key {key!r} in state_dict")
        self.used.add(key)
        return self.sd[key]

    def bn(self, prefix: str) -> tuple[dict, dict]:
        p = {"gamma": _np(self.take(f"{prefix}.weight")), "beta": _np(self.take(f"{prefix}.bias"))}
        s = {"mean": _np(self.take(f"{prefix}.running_mean")), "var": _np(self.take(f"{prefix}.running_var"))}
        self.used.add(f"{prefix}.num_batches_tracked")  # present in torch, meaningless here
        return p, s

    def leftovers(self) -> list[str]:
        return [k for k in self.sd if k not in self.used]


def _check(name: str, got: np.ndarray, want_shape: tuple[int, ...]):
    if tuple(got.shape) != tuple(want_shape):
        raise CheckpointImportError(f"{name}: checkpoint shape {tuple(got.shape)} != model shape {tuple(want_shape)}")
    return got


def from_torchvision_mobilenet_v2(state_dict: Mapping[str, Any], net: Network) -> tuple[dict, dict]:
    """Returns (params, state) for ``net`` from a torchvision-MobileNetV2-layout
    state_dict. Strict: every model leaf must be filled and every checkpoint
    tensor consumed (except ``num_batches_tracked``)."""
    sd = _SD(state_dict)
    params: dict = {}
    state: dict = {}

    # stem
    w = _conv_w(sd.take("features.0.0.weight"))
    k = net.stem.kernel_size
    params["stem"] = {"conv": {"w": _check("stem.conv", w, (k, k, 3, net.stem.out_channels))}}
    bn_p, bn_s = sd.bn("features.0.1")
    params["stem"]["bn"], state["stem"] = bn_p, {"bn": bn_s}

    # blocks: our blocks[i] == torchvision features[i+1]
    bp: dict = {}
    bs: dict = {}
    for i, blk in enumerate(net.blocks):
        f = f"features.{i + 1}.conv"
        if len(blk.kernel_sizes) != 1:
            raise CheckpointImportError(f"block {i}: multi-kernel supernet blocks are not a torchvision layout")
        kd = blk.kernel_sizes[0]
        e = blk.expanded_channels
        p: dict = {}
        s: dict = {}
        if blk.has_expand:
            p["expand"] = {
                "w": _check(f"block{i}.expand", _conv_w(sd.take(f"{f}.0.0.weight")), (1, 1, blk.in_channels, e))
            }
            p["expand_bn"], s["expand_bn"] = sd.bn(f"{f}.0.1")
            dw, proj = f"{f}.1", 2
        else:
            dw, proj = f"{f}.0", 1
        p[f"dw0_k{kd}"] = {
            "w": _check(f"block{i}.dw", _conv_w(sd.take(f"{dw}.0.weight")), (kd, kd, 1, e))
        }
        p["dw_bn"], s["dw_bn"] = sd.bn(f"{dw}.1")
        p["project"] = {
            "w": _check(f"block{i}.project", _conv_w(sd.take(f"{f}.{proj}.weight")), (1, 1, e, blk.out_channels))
        }
        p["project_bn"], s["project_bn"] = sd.bn(f"{f}.{proj + 1}")
        bp[str(i)], bs[str(i)] = p, s
    params["blocks"], state["blocks"] = bp, bs

    # head
    if net.head is None:
        raise CheckpointImportError("MobileNetV2 layout requires a head conv")
    hi = len(net.blocks) + 1
    w = _conv_w(sd.take(f"features.{hi}.0.weight"))
    params["head"] = {
        "conv": {"w": _check("head.conv", w, (1, 1, net.head.in_channels, net.head.out_channels))}
    }
    bn_p, bn_s = sd.bn(f"features.{hi}.1")
    params["head"]["bn"], state["head"] = bn_p, {"bn": bn_s}

    # classifier (torchvision: classifier = Sequential(Dropout, Linear))
    cw = _np(sd.take("classifier.1.weight")).T  # (out,in) -> (in,out)
    cb = _np(sd.take("classifier.1.bias"))
    params["classifier"] = {
        "w": _check("classifier.w", cw, (net.classifier.in_features, net.classifier.out_features)),
        "b": _check("classifier.b", cb, (net.classifier.out_features,)),
    }

    left = sd.leftovers()
    if left:
        raise CheckpointImportError(f"unconsumed checkpoint tensors: {left[:8]}{'...' if len(left) > 8 else ''}")

    import jax
    import jax.numpy as jnp

    return jax.tree.map(jnp.asarray, params), jax.tree.map(jnp.asarray, state)


def from_torchvision_mobilenet_v3(state_dict: Mapping[str, Any], net: Network) -> tuple[dict, dict]:
    """torchvision MobileNetV3 layout: blocks live under
    ``features.{i+1}.block.{j}`` (expand / depthwise / SqueezeExcitation /
    project sub-modules, SE as 1x1 convs fc1/fc2 WITH bias), the head conv at
    ``features.{n+1}``, and the classifier as
    ``classifier.0`` (the 1280-wide "feature" Linear) + ``classifier.3``.

    Parity note: torchvision V3 BatchNorms use eps=1e-3 (momentum 0.01) —
    build the target net with ``model.bn_eps=1e-3`` or evals will drift
    (warned below, since the CLI user never sees this docstring)."""
    if abs(net.stem.bn_eps - 1e-3) > 1e-12:
        import warnings

        warnings.warn(
            f"importing a torchvision-V3-layout checkpoint into a net with bn_eps={net.stem.bn_eps} "
            "— torchvision MobileNetV3 uses bn_eps=1e-3; set model.bn_eps=1e-3 or top-1 will drift",
            stacklevel=2,
        )
    sd = _SD(state_dict)
    params: dict = {}
    state: dict = {}

    w = _conv_w(sd.take("features.0.0.weight"))
    k = net.stem.kernel_size
    params["stem"] = {"conv": {"w": _check("stem.conv", w, (k, k, 3, net.stem.out_channels))}}
    bn_p, bn_s = sd.bn("features.0.1")
    params["stem"]["bn"], state["stem"] = bn_p, {"bn": bn_s}

    bp: dict = {}
    bs: dict = {}
    for i, blk in enumerate(net.blocks):
        f = f"features.{i + 1}.block"
        if len(blk.kernel_sizes) != 1:
            raise CheckpointImportError(f"block {i}: multi-kernel supernet blocks are not a torchvision layout")
        kd = blk.kernel_sizes[0]
        e = blk.expanded_channels
        p: dict = {}
        s: dict = {}
        j = 0
        if blk.has_expand:
            p["expand"] = {
                "w": _check(f"block{i}.expand", _conv_w(sd.take(f"{f}.{j}.0.weight")), (1, 1, blk.in_channels, e))
            }
            p["expand_bn"], s["expand_bn"] = sd.bn(f"{f}.{j}.1")
            j += 1
        p[f"dw0_k{kd}"] = {
            "w": _check(f"block{i}.dw", _conv_w(sd.take(f"{f}.{j}.0.weight")), (kd, kd, 1, e))
        }
        p["dw_bn"], s["dw_bn"] = sd.bn(f"{f}.{j}.1")
        j += 1
        if blk.se_channels:
            se = blk.se_channels
            fc1 = _np(sd.take(f"{f}.{j}.fc1.weight"))[:, :, 0, 0].T  # (se,C,1,1) -> (C,se)
            fc2 = _np(sd.take(f"{f}.{j}.fc2.weight"))[:, :, 0, 0].T  # (C,se,1,1) -> (se,C)
            p["se"] = {
                "reduce": {"w": _check(f"block{i}.se.fc1", fc1, (e, se)),
                           "b": _np(sd.take(f"{f}.{j}.fc1.bias"))},
                "expand": {"w": _check(f"block{i}.se.fc2", fc2, (se, e)),
                           "b": _np(sd.take(f"{f}.{j}.fc2.bias"))},
            }
            j += 1
        p["project"] = {
            "w": _check(f"block{i}.project", _conv_w(sd.take(f"{f}.{j}.0.weight")), (1, 1, e, blk.out_channels))
        }
        p["project_bn"], s["project_bn"] = sd.bn(f"{f}.{j}.1")
        bp[str(i)], bs[str(i)] = p, s
    params["blocks"], state["blocks"] = bp, bs

    if net.head is None or net.feature is None:
        raise CheckpointImportError("MobileNetV3 layout requires head conv + feature Linear")
    hi = len(net.blocks) + 1
    w = _conv_w(sd.take(f"features.{hi}.0.weight"))
    params["head"] = {
        "conv": {"w": _check("head.conv", w, (1, 1, net.head.in_channels, net.head.out_channels))}
    }
    bn_p, bn_s = sd.bn(f"features.{hi}.1")
    params["head"]["bn"], state["head"] = bn_p, {"bn": bn_s}

    params["feature"] = {
        "w": _check("feature.w", _np(sd.take("classifier.0.weight")).T,
                    (net.feature.in_features, net.feature.out_features)),
        "b": _check("feature.b", _np(sd.take("classifier.0.bias")), (net.feature.out_features,)),
    }
    params["classifier"] = {
        "w": _check("classifier.w", _np(sd.take("classifier.3.weight")).T,
                    (net.classifier.in_features, net.classifier.out_features)),
        "b": _check("classifier.b", _np(sd.take("classifier.3.bias")), (net.classifier.out_features,)),
    }

    left = sd.leftovers()
    if left:
        raise CheckpointImportError(f"unconsumed checkpoint tensors: {left[:8]}{'...' if len(left) > 8 else ''}")

    import jax
    import jax.numpy as jnp

    return jax.tree.map(jnp.asarray, params), jax.tree.map(jnp.asarray, state)


def load_torch_checkpoint(path: str, net: Network) -> tuple[dict, dict]:
    """Loads a .pth/.pt file (a raw state_dict or a dict holding one under
    'state_dict'/'model') and imports it into ``net``'s tree layout. The
    torchvision layout (V2 `.conv.` vs V3 `.block.`) is auto-detected."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict) and not any(hasattr(v, "shape") for v in obj.values()):
        for key in ("state_dict", "model", "model_state"):
            if key in obj:
                obj = obj[key]
                break
    # strip DistributedDataParallel's 'module.' prefix if present
    obj = {k.removeprefix("module."): v for k, v in obj.items()}
    if any(".block." in k for k in obj):
        return from_torchvision_mobilenet_v3(obj, net)
    return from_torchvision_mobilenet_v2(obj, net)
