"""Typed, immutable experiment configuration.

Replaces the reference's ``utils/config.py`` global-``FLAGS`` AttrDict
(SURVEY.md §2 #2) with frozen dataclasses passed explicitly.  The YAML surface
stays reference-compatible in spirit:

- experiments live in ``apps/*.yml`` and are selected with an ``app:<path>``
  CLI argument,
- a YAML file may inherit from another via a top-level ``_base_: <relpath>``
  key (deep-merged, child wins),
- remaining CLI args of the form ``a.b.c=value`` override individual keys.

Unknown keys are an error — silent typos in a 350-epoch run are expensive.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field, fields
from typing import Any, Mapping, Sequence

import yaml

# ---------------------------------------------------------------------------
# YAML loading with _base_ inheritance
# ---------------------------------------------------------------------------


def _deep_merge(base: dict, override: dict) -> dict:
    """Recursively merge ``override`` into ``base`` (override wins)."""
    out = dict(base)
    for k, v in override.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def load_yaml(path: str, _seen: tuple = ()) -> dict:
    """Load a YAML file, resolving ``_base_`` inheritance chains."""
    path = os.path.abspath(path)
    if path in _seen:
        raise ValueError(f"circular _base_ inheritance: {path}")
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: top-level YAML must be a mapping")
    base_rel = raw.pop("_base_", None)
    if base_rel is not None:
        base_path = os.path.join(os.path.dirname(path), base_rel)
        base = load_yaml(base_path, _seen + (path,))
        raw = _deep_merge(base, raw)
    return raw


# ---------------------------------------------------------------------------
# Config schema
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ModelConfig:
    """Architecture selection.

    ``arch`` names a built-in block-spec (models/zoo.py); ``block_specs``
    overrides it with an explicit list (the reference expressed searched /
    supernet architectures as YAML block-spec lists, SURVEY.md §2 #5 #14).
    """

    arch: str = "mobilenet_v2"
    num_classes: int = 1000
    width_mult: float = 1.0
    dropout: float = 0.2
    # Explicit block specs override `arch`. Each entry is a mapping accepted
    # by models.specs.BlockSpec.from_dict.
    block_specs: Sequence[Mapping[str, Any]] | None = None
    # Path to a serialized Network (e.g. a search run's searched_arch.json);
    # overrides arch/block_specs entirely — this is how an emitted AtomNAS
    # result is trained/evaluated as a standalone model.
    network_spec: str = ""
    # Stem / head channel overrides (None = arch default).
    # EXACT final widths when set — exempt from width_mult scaling
    # (models/specs.py build_network); None = the arch default, scaled
    stem_channels: int | None = None
    head_channels: int | None = None
    feature_channels: int | None = None
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    # Stochastic-depth max rate (EfficientNet drop_connect); None = the
    # arch's default (0 everywhere except efficientnet_b0's paper 0.2).
    # Per-block rates ramp linearly with depth (models/specs.py).
    drop_connect: float | None = None
    # Overrides the arch's default activation when set (e.g. swish for the
    # AtomNAS "+" variants); None = keep the arch's own default.
    active_fn: str | None = None
    # If true, classifier bias is zero-initialized (standard).
    dtype: str = "float32"  # param dtype; compute may be bf16 (train.compute_dtype)


@dataclass(frozen=True)
class DataConfig:
    dataset: str = "imagenet"  # imagenet | fake | folder
    data_dir: str = ""
    train_split: str = "train"
    val_split: str = "validation"
    image_size: int = 224
    eval_resize: int = 256
    num_train_examples: int = 1281167
    num_eval_examples: int = 50000
    # fake dataset knobs (integration tests / benches without ImageNet)
    fake_num_classes: int | None = None
    fake_train_size: int = 6400
    fake_eval_size: int = 640
    # input pipeline
    loader: str = "tfdata"  # tfdata | native | synthetic
    shuffle_buffer: int = 16384
    prefetch: int = 4  # host-side tf.data prefetch depth
    # device-HBM prefetch depth (batches pinned on the mesh ahead of compute;
    # independent of the host-side knob — each unit costs a full global batch
    # of HBM)
    device_prefetch: int = 2
    decode_threads: int = 8
    # augmentation (Inception-style random-resized-crop defaults)
    rrc_area_min: float = 0.08
    rrc_area_max: float = 1.0
    rrc_ratio_min: float = 0.75
    rrc_ratio_max: float = 1.3333333333333333
    color_jitter: float = 0.0  # brightness/contrast/saturation strength, 0=off
    # RandAugment (arXiv:1909.13719, beyond reference parity; the
    # EfficientNet recipe trains with layers=2): N stateless position-keyed
    # ops per image at magnitude M (0..10, the official _MAX_LEVEL scale).
    # tf.data pipelines only — the native C++ loader rejects it.
    randaugment_layers: int = 0  # 0 = off
    randaugment_magnitude: int = 10
    # bitwise-reproducible TFRecord streams: single-stream deterministic
    # interleave, no record shuffle buffer (the stateless (seed, epoch)
    # file permutation is the shuffle). Augmentations are stateless (keyed
    # by stream position), so resume and rebuilds reproduce PIXELS, not
    # just record order — at host decode-parallelism cost. Off = production
    # throughput with the one-buffer resume approximation
    # (data/pipeline.py make_train_dataset).
    deterministic_input: bool = False
    mean: Sequence[float] = (0.485, 0.456, 0.406)
    std: Sequence[float] = (0.229, 0.224, 0.225)
    # survive corrupt/undecodable records: a batch lost to a decode error is
    # skipped and counted (data.corrupt_records) instead of killing the run;
    # max_consecutive_failures consecutive lost batches abort loudly (a fully
    # rotten shard must not spin forever). tf.data loses the whole batch the
    # record landed in; the native C++ loader skips at record granularity and
    # counts data.decode_failures (data/pipeline.py resilient_batches).
    skip_corrupt_records: bool = True
    max_consecutive_failures: int = 16
    # host-side background prefetch thread between the pipeline and the
    # device-prefetch stage: decouples batch production from the train loop
    # and survives worker crashes with a bounded restart
    # (data/pipeline.py PrefetchWorker; crash guard per yamt-lint YAMT011)
    prefetch_thread: bool = False
    # ship images host->device as uint8 and normalize IN-STEP (on device)
    # instead of shipping normalized f32: 4x less PCIe/transfer volume. At
    # the v4-32 acceptance point the f32 feed costs ~34 GB/s/host (57k
    # img/s/host x 602 KB) — above PCIe4 x16 — while uint8 is ~8.6 GB/s
    # (BASELINE.md "transfer_uint8": also a measured 1.72x HOST pipeline
    # win — no host-side normalize, 4x smaller buffers). The reference's
    # DALI decodes on-GPU and never pays this. Cost: post-augment float
    # pixels round to u8 (<=0.5/255 quantization, under JPEG decode noise;
    # equivalence pinned by tests). Real-JPEG pipelines only (tfdata
    # TFRecords and the native C++ loader; fake data lives in normalized
    # space and is rejected at dispatch).
    transfer_uint8: bool = False


@dataclass(frozen=True)
class OptimConfig:
    optimizer: str = "rmsprop"  # rmsprop | sgd | adamw
    momentum: float = 0.9
    # TF-style RMSProp constants (eps inside the sqrt; SURVEY.md §7 hard part 2)
    rmsprop_decay: float = 0.9
    rmsprop_eps: float = 0.002
    # TF momentum ordering: mom = m*mom + lr*g/sqrt(nu+eps), i.e. each step's
    # LR is baked into the buffer at accumulation time, so past contributions
    # keep their old LR across decay boundaries. False = torch-RMSprop
    # ordering (LR multiplies the whole buffer at apply time); the two only
    # differ while LR changes.
    rmsprop_tf_momentum_order: bool = True
    weight_decay: float = 1e-5
    # weight-decay exemptions, reference-style (SURVEY.md §2 #7)
    wd_skip_bn: bool = True
    wd_skip_bias: bool = True
    wd_skip_depthwise: bool = False
    label_smoothing: float = 0.1
    grad_clip_norm: float = 0.0  # 0 = off
    # Mixup (arXiv:1710.09412) / CutMix (arXiv:1905.04899) — beyond
    # reference parity, applied IN-STEP on device (train/steps.py
    # make_batch_mixer): zero host-pipeline cost, decorrelated per replica.
    # 0 = off; when both are set, each step picks one with p=0.5.
    mixup_alpha: float = 0.0
    cutmix_alpha: float = 0.0


@dataclass(frozen=True)
class ScheduleConfig:
    """LR schedule; stepped per-iteration (SURVEY.md §2 #9)."""

    schedule: str = "exp_decay"  # exp_decay | cosine | constant
    base_lr: float = 0.064  # scaled by total_batch/256 if scale_by_batch
    scale_by_batch: bool = True
    warmup_epochs: float = 5.0
    # exp_decay: lr *= decay_rate every decay_epochs
    decay_rate: float = 0.963
    decay_epochs: float = 3.0
    # cosine
    final_lr_factor: float = 0.0


@dataclass(frozen=True)
class EMAConfig:
    enable: bool = True
    decay: float = 0.9999
    # TF-style warmup: effective decay = min(decay, (1+t)/(10+t))
    warmup: bool = True


@dataclass(frozen=True)
class PruneConfig:
    """AtomNAS dynamic shrinkage (SURVEY.md §2 #11, §3.2)."""

    enable: bool = False
    # penalty weight on FLOPs-weighted BN-gamma L1
    rho: float = 1.8e-4
    # |gamma| below this is dead
    gamma_threshold: float = 1e-3
    # steps between in-jit mask refreshes
    mask_interval: int = 500
    # epochs between physical shape rematerializations (0 = never)
    remat_epochs: float = 25.0
    # stop pruning after this fraction of training (paper stops to stabilize)
    stop_epoch_frac: float = 0.5
    # optional FLOPs floor: stop masking when effective FLOPs reach target
    target_flops: float = 0.0
    # normalize per-channel flops cost by total network flops
    normalize_cost: bool = True
    # atom cost source weighting the BN-gamma L1 (ROADMAP item 3): "flops"
    # (analytic MACs, the AtomNAS default) or "latency_table" (MEASURED
    # per-block latency slopes from a scripts/latency_table.py artifact —
    # FLOPs is a poor latency proxy, PAPERS.md FLASH/LANA). Flag-gated: the
    # default search objective is unchanged.
    cost: str = "flops"
    # LATENCY_TABLE_*.json path (required when cost="latency_table"); every
    # prunable block of the net must have a measured entry (nas/latency.py)
    latency_table: str = ""
    # rho dynamics (SURVEY.md §2 #11 "penalty weight (rho) schedule"):
    #   constant — rho as-is
    #   ramp     — linear 0 -> rho over the first rho_ramp_epochs
    #   adaptive — ramp, then multiplicative feedback on the FLOPs gap at the
    #              mask cadence: x(1+rate) while effective MACs > target_flops,
    #              x(1-rate) once at/below (anneal), clamped to
    #              [rho_adapt_min, rho_adapt_max] x rho. Requires target_flops.
    rho_schedule: str = "constant"
    rho_ramp_epochs: float = 0.0
    rho_adapt_rate: float = 0.05
    rho_adapt_min: float = 0.1
    rho_adapt_max: float = 10.0


@dataclass(frozen=True)
class GuardConfig:
    """Step health guard (train/guard.py): skip-and-count non-finite steps by
    restoring the pre-step TrainState IN-PROGRAM (a device-side select — no
    extra host syncs; the host reads the verdicts once per train.log_every
    boundary), abort with a train_health.json dump when the bound is
    exceeded. Off by default: the legacy behavior (abort on the first
    non-finite loss seen at a log boundary) is the conservative debug
    default; long production runs enable the guard so one bad batch costs
    one step, not the job."""

    enable: bool = False
    # total non-finite (skipped) steps tolerated per run before the guard
    # aborts with TrainHealthError + train_health.json
    max_skipped_steps: int = 10


@dataclass(frozen=True)
class TrainFaultsConfig:
    """Deterministic, seeded fault injection around the TRAIN data stream
    (train/faults.py) — the training twin of serve/faults.py: every recovery
    path (corrupt-record skip, non-finite step rollback, loader-stall
    watchdog, SIGTERM preemption checkpoint) is dead code until something
    fails, and chaos must be reproducible. Off in production."""

    enable: bool = False
    seed: int = 0
    # per-pull probability of raising CorruptRecordError instead of a batch
    # (exercises data.skip_corrupt_records + data.corrupt_records counting)
    corrupt_record_rate: float = 0.0
    # global step indices whose batch gets a NaN poisoned in (exercises the
    # train.guard rollback); () = never
    nan_at_steps: Sequence[int] = ()
    # stall the loader for stall_ms at this global step (watchdog drill);
    # -1 = never
    stall_at_step: int = -1
    stall_ms: float = 0.0
    # send THIS process SIGTERM after serving this global step's batch
    # (deterministic preemption drill); -1 = never
    kill_at_step: int = -1


@dataclass(frozen=True)
class TrainConfig:
    epochs: float = 350.0
    batch_size: int = 256  # GLOBAL batch size (split across data-parallel chips)
    eval_batch_size: int = 250
    seed: int = 0
    compute_dtype: str = "bfloat16"  # matmul/conv compute dtype on TPU
    # jax.checkpoint the forward pass: recompute activations in backward to
    # trade FLOPs for HBM (enables larger per-chip batches)
    remat: bool = False
    # remat flavor: "full" recomputes everything from the inputs; "save_conv"
    # saves the conv (MXU) outputs and recomputes only the BN/act elementwise
    # chains — targets the BN activation round-trips without re-running convs
    remat_policy: str = "full"
    # BatchNorm normalize expression: "exact" (f32, reference semantics),
    # "folded" (precomputed f32 scale/bias FMA), "compute" (FMA in the
    # compute dtype), "fused_vjp" (folded forward + closed-form custom
    # backward with pinned bf16 residuals). Statistics are identical f32 in
    # every mode; this knob targets the 52% BN-reduction share of the
    # round-2 TPU trace (PROFILE.md). See ops/layers.py BatchNorm.apply.
    bn_mode: str = "exact"
    # lower 1x1 ungrouped convs as explicit matmuls so their weight grads
    # are guaranteed MXU dots — targets the 25.3% multiply_add_fusion
    # weight-grad share of the round-2 trace (ops/layers.py Conv2D.apply)
    conv1x1_dot: bool = False
    log_every: int = 100
    eval_every_epochs: float = 1.0
    checkpoint_every_epochs: float = 1.0
    max_checkpoints: int = 3
    # keep a single best-eval-top1 checkpoint in log_dir/ckpt_best (the
    # reference lineage's best.pth); resumable/evaluable like any checkpoint
    keep_best: bool = True
    log_dir: str = "/tmp/yamt_logs"
    resume: bool = True
    test_only: bool = False
    pretrained: str = ""  # checkpoint path for eval/finetune
    # torch .pth state_dict (torchvision MobileNetV2 layout) to import for
    # eval — acceptance #1 against real pretrained weights (ckpt/torch_import)
    torch_pretrained: str = ""
    # debug guards (SURVEY.md §5 race-detection analogue)
    check_finite_every: int = 0  # 0 = off
    param_checksum_every: int = 0  # cross-replica divergence check, 0 = off
    # jax.profiler trace capture (SURVEY.md §5 tracing): start at this step
    # for profile_num_steps steps; trace lands in log_dir/trace. 0 = off.
    profile_start_step: int = 0
    profile_num_steps: int = 5
    # >1: run this many train steps per host dispatch (one jit call of k
    # unrolled steps) to amortize per-step dispatch/tunnel latency —
    # adopt when bench_bn's --dispatch-probe shows a real tax. Same data
    # order/RNG/resume accounting as single dispatches; numerics agree to
    # XLA cross-step fusion rounding ~1e-7 (parallel/dp.py
    # make_grouped_train_step). Composes with pruning (the prune event runs
    # in-device after every unrolled sub-step, nas/masking.make_prune_event);
    # only the profiler window (host start/stop_trace at exact steps) still
    # forces 1 with a logged warning.
    steps_per_dispatch: int = 1
    # path to a BENCH_TUNING.json-format file (written by the tpu_watch
    # measurement watcher's adoption step): its step-config keys (bn_mode,
    # remat, remat_policy, conv1x1_dot, steps_per_dispatch) and XLA flags
    # override this config at startup with provenance logged — measured
    # winners reach production runs without hand-editing YAML
    # (train/tuning.py; eval accuracy is immune: eval always runs exact BN
    # + stock conv lowering). "" = off.
    tuning_file: str = ""
    # step health guard + train-side chaos injection sub-blocks
    guard: GuardConfig = field(default_factory=GuardConfig)
    faults: TrainFaultsConfig = field(default_factory=TrainFaultsConfig)


@dataclass(frozen=True)
class ObsConfig:
    """Runtime telemetry (obs/): span tracing, metrics registry, stall
    watchdog — docs/OBSERVABILITY.md. The registry is always on (it is just
    counters); tracing and the watchdog are opt-in knobs."""

    # coordinator-only span tracer; Chrome-trace JSON lands in
    # log_dir/obs_trace.json at run end (or on crash). Composes with
    # train.steps_per_dispatch > 1 — spans time the HOST side of dispatches,
    # unlike the jax.profiler window which forces k=1.
    trace: bool = False
    # completed spans kept in the ring buffer (oldest evicted); one span is
    # a ~100-byte tuple, so the default retains the last few thousand events
    # of a multi-day run for bounded memory
    trace_ring_size: int = 4096
    # histogram bucket ladder (upper bounds, seconds) for registry
    # histograms created after startup; () = the built-in quarter-decade
    # log ladder 100µs..~56s (obs/registry.py DEFAULT_BUCKET_BOUNDS). The
    # ladder sets quantile-estimate resolution: p50/p95/p99 interpolate
    # inside one bucket, so error is bounded by that bucket's width.
    histogram_buckets: Sequence[float] = ()
    # no train-loop heartbeat (step / eval / checkpoint / rematerialize
    # progress) for this long -> hang_report.json in log_dir. 0 = off.
    # Must exceed the slowest legitimate gap: the first step's compile and
    # the longest eval/checkpoint phase (docs/OBSERVABILITY.md tuning).
    watchdog_deadline_s: float = 0.0
    # watchdog check interval; 0 = auto (deadline/4, clamped to [0.05s, 1s])
    watchdog_poll_s: float = 0.0


@dataclass(frozen=True)
class ListenConfig:
    """Loopback HTTP front door (serve/frontend.py, cli/serve.py --listen):
    POST /predict with priority + deadline headers, GET /healthz reporting
    breaker + queue state — docs/SERVING.md "Front door"."""

    enable: bool = False
    host: str = "127.0.0.1"
    # 0 = ephemeral; the bound port is logged and written to
    # <log_dir>/listen_addr.json so callers never race the bind
    port: int = 0
    # server-side cap on how long one /predict handler waits for its result
    # when the request carries no deadline (a deadline extends this bound)
    request_timeout_s: float = 60.0
    # xplane dump dir for the HTTP-triggered profiler capture
    # (POST /profile/start|stop, obs/device.py ProfilerCapture);
    # "" = <train.log_dir>/trace (endpoints 404 when neither is set)
    profile_dir: str = ""
    # stable replica name reported in the /healthz + /varz identity block
    # (replica_id/pid/start_unix/git_sha) so a router can attribute health
    # and detect a restarted process behind the same address; "" = pid-<pid>.
    # A fleet supervisor (cli/fleet.py) assigns r<i> per slot.
    replica_id: str = ""
    # router address ("host:port") this replica REGISTERS itself with: a
    # heartbeat thread POSTs /register every register_ttl_s/3 so the lease
    # never lapses while the process lives, and /deregister on drain. ""
    # = no self-registration (supervisor-spawned replicas are pushed into
    # the router by membership notifications instead). This is how a
    # replica on ANOTHER HOST joins a fleet that never spawned it.
    register_to: str = ""
    # TTL requested per /register heartbeat; expiry removes the backend
    register_ttl_s: float = 3.0


@dataclass(frozen=True)
class AdmissionConfig:
    """Priority/QoS admission control + resilience in front of the batcher
    (serve/admission.py): per-class weighted queue shares, deadline-aware
    reject-on-arrival, bounded retry with jittered backoff, circuit breaker."""

    # class a request lands in when it names none (requests naming an
    # unknown class are rejected, not silently reclassified)
    default_class: str = "interactive"
    # queue-share weights for (interactive, batch, best_effort): each class
    # gets at least ceil(queue_depth * w / sum(w)) slots, so best-effort
    # floods can never starve interactive admission
    weights: Sequence[float] = (8.0, 3.0, 1.0)
    # bounded retry of TRANSIENT engine failures (inference is pure, so a
    # retry can never double-apply anything); 0 = fail on first error
    max_retries: int = 2
    retry_backoff_ms: float = 5.0  # doubles per attempt
    retry_jitter: float = 0.5  # +/- fraction of the backoff, desynchronizes herds
    # consecutive engine failures (across requests) that open the breaker
    breaker_threshold: int = 5
    # open -> half-open delay; half-open admits ONE probe before closing
    breaker_cooldown_s: float = 1.0
    # EWMA smoothing for observed request latency (the arrival-time wait
    # predictor feeding reject_unmeetable)
    ewma_alpha: float = 0.2
    # reject-on-arrival when the predicted wait already exceeds the request's
    # deadline: cheaper than shedding it after it burned a queue slot
    reject_unmeetable: bool = True
    # wait predictor feeding reject_unmeetable: "ewma" (smoothed mean — the
    # original; tracks the center, blind to the tail) or "quantile" (the
    # predictor_quantile of the class's bucketed serve.latency_seconds
    # histogram — deadline decisions keyed on measured TAIL latency; falls
    # back to the EWMA until the class histogram has data)
    predictor: str = "ewma"
    predictor_quantile: float = 0.9


@dataclass(frozen=True)
class FaultsConfig:
    """Deterministic, seeded fault injection around the engine
    (serve/faults.py) — chaos testing the admission/retry/breaker stack with
    reproducible failure schedules. Off in production."""

    enable: bool = False
    seed: int = 0
    # per-dispatch failure probability (seeded draw, deterministic in
    # dispatch order)
    failure_rate: float = 0.0
    # fail the first N dispatches then recover (breaker-drill schedule)
    fail_first_n: int = 0
    # where injected failures surface: at dispatch (collect thread) or at
    # result() (completion thread)
    fail_at: str = "dispatch"  # dispatch | result
    # injected completion latency, applied with probability latency_rate
    latency_ms: float = 0.0
    latency_rate: float = 1.0
    # dispatches that run CLEAN before the latency injection begins: a
    # replica that degrades mid-run (the gray-failure drill — the router
    # learned its baseline while it was healthy). 0 = degraded from birth
    latency_after_n: int = 0
    # dispatch index that HANGS until FaultyEngine.hang_release is set
    # (drain-timeout / watchdog drills); -1 = never
    hang_at: int = -1


@dataclass(frozen=True)
class HedgeConfig:
    """Request hedging (serve/hedge.py): duplicate a straggler to a second
    replica after a timer DERIVED from the router's measured per-class
    latency (the p-quantile of serve.router.latency_seconds.<class>), first
    answer wins, loser dropped idempotently — docs/SERVING.md "Fleet"."""

    enable: bool = True
    # the latency quantile the hedge timer fires at (0.99 = only the worst
    # ~1% of requests ever cost a duplicate)
    quantile: float = 0.99
    # per-class observations required before hedging arms (a cold fleet
    # must not hedge on garbage estimates)
    min_samples: int = 20
    # timer clamp: never hedge faster than min (herd protection) or wait
    # longer than max (a wedged replica must not pin its requests forever)
    min_timer_ms: float = 10.0
    max_timer_ms: float = 2000.0


@dataclass(frozen=True)
class AutoscaleConfig:
    """Fleet autoscaler (serve/autoscale.py): a control thread scaling the
    replica count off the /metrics tail-latency + queue-depth families with
    cooldown hysteresis. Off by default: a fixed-N fleet is the predictable
    baseline; enable for diurnal traffic."""

    enable: bool = False
    min_replicas: int = 1
    max_replicas: int = 4
    interval_s: float = 1.0
    # no second scaling action within this window of the previous one —
    # a spawn needs seconds to absorb load, and flapping costs a compile
    cooldown_s: float = 5.0
    # scale-up triggers (either): window p99 of the router latency family
    # above up_p99_ms, or mean routable queue depth above up_queue_depth
    up_p99_ms: float = 250.0
    up_queue_depth: float = 8.0
    # scale-down requires BOTH below these (strictly under the up
    # thresholds — the dead band between them is the hysteresis)
    down_p99_ms: float = 50.0
    down_queue_depth: float = 1.0
    # the class whose serve.router.latency_seconds histogram is the tail
    # signal (interactive = the traffic with an SLO)
    signal_class: str = "interactive"


@dataclass(frozen=True)
class FleetChaosConfig:
    """Replica-level chaos (cli/fleet.py): a seeded schedule of kill -9 OR
    gray degradation against live replicas mid-load — the process-granular
    twin of serve/faults.py's in-process injection. The supervisor's
    restart-on-exit, the router's ejection/retry, and (degrade mode) the
    latency-based soft ejection are dead code until a replica actually dies
    or limps. Off in production."""

    enable: bool = False
    seed: int = 0
    # "kill" = crash chaos (the signal below); "degrade" = gray-failure
    # chaos: the seeded victim is SIGSTOP/SIGCONT-pulsed so it stays alive
    # but slow (a GC-pause/noisy-neighbor stand-in) — the router must
    # soft-eject it on measured latency, never on a crash signal;
    # "partition" = NETWORK chaos: the seeded victim's netchaos proxy
    # (serve.fleet.netchaos must be enabled) is switched to the configured
    # fault shape for degrade_duration_s, then healed — the process never
    # even notices, only the link misbehaves
    mode: str = "kill"
    # first kill/degradation this long after the fleet is up
    kill_after_s: float = 2.0
    # subsequent kills every this often; 0 = exactly one kill (kill mode)
    kill_period_s: float = 0.0
    # "kill" = SIGKILL (no drain, the real chaos); "term" = SIGTERM
    # (graceful — drills the drain path instead)
    signal: str = "kill"
    # degrade mode: pulse shape (stopped degrade_stop_ms out of every
    # degrade_period_ms) and how long the episode lasts
    degrade_stop_ms: float = 150.0
    degrade_period_ms: float = 500.0
    degrade_duration_s: float = 10.0

    def __post_init__(self):
        if self.mode not in ("kill", "degrade", "partition"):
            raise ValueError(
                f"fleet.chaos.mode must be kill|degrade|partition, got {self.mode!r}")
        if not 0.0 < self.degrade_stop_ms < self.degrade_period_ms:
            raise ValueError("fleet.chaos needs 0 < degrade_stop_ms < degrade_period_ms")


@dataclass(frozen=True)
class NetChaosConfig:
    """Socket-level network chaos (serve/netchaos.py): a seeded TCP fault-
    injection proxy interposed between the router and EACH replica, so
    every partition shape — blackhole, reset, half-open, latency/jitter,
    throttle, asymmetric response loss, timed flaps — is reproducible on
    one box without root/iptables. ``enable`` inserts the proxy tier
    (pass-through until a fault is armed); FleetChaos ``mode="partition"``
    flips the configured ``fault`` on a seeded victim on its schedule."""

    enable: bool = False
    seed: int = 0
    # the shape mode="partition" injects on the victim link
    fault: str = "blackhole"  # blackhole | reset | half_open | drop_response
    # fraction of connections the fault applies to (seeded per-connection
    # draw); 1.0 = a link-level fault that spares nothing
    fault_rate: float = 1.0
    # response-path shaping, applied whenever the link is up
    latency_ms: float = 0.0
    jitter_ms: float = 0.0
    bandwidth_kbps: float = 0.0
    # timed link flaps: down (blackhole) flap_down_s out of every
    # flap_period_s; 0 = no flapping
    flap_period_s: float = 0.0
    flap_down_s: float = 0.0

    def __post_init__(self):
        if self.fault not in ("blackhole", "reset", "half_open", "drop_response"):
            raise ValueError(
                "fleet.netchaos.fault must be blackhole|reset|half_open|drop_response, "
                f"got {self.fault!r}")
        if not 0.0 <= self.fault_rate <= 1.0:
            raise ValueError(
                f"fleet.netchaos.fault_rate must be in [0, 1], got {self.fault_rate}")
        if self.flap_period_s > 0 and not 0.0 < self.flap_down_s < self.flap_period_s:
            raise ValueError("fleet.netchaos needs 0 < flap_down_s < flap_period_s")


@dataclass(frozen=True)
class SlowEjectConfig:
    """Gray-failure soft ejection (serve/router.py): a replica whose per-leg
    latency EWMA is a multiplicative outlier vs the fleet median first has
    its routing weight decayed, then is ejected (``fleet.slow_ejections``)
    and readmitted through the healthy poll after a probation cooldown —
    the latency twin of crash ejection, for the straggler that never dies."""

    enable: bool = True
    # outlier bound: ejectable when EWMA > slow_factor x fleet (lower) median
    slow_factor: float = 3.0
    # consecutive outlier poll-sweeps before ejection (weight decays first)
    eject_after: int = 3
    # probation: a slow-ejected replica stays out at least this long; the
    # next healthy poll after it readmits with a FRESH latency estimate
    cooldown_s: float = 5.0
    # absolute floor on the outlier threshold: sub-ms jitter between fast
    # replicas must never look like a gray failure
    min_ms: float = 1.0
    # EWMA smoothing for the per-replica per-leg latency estimate
    lat_alpha: float = 0.3

    def __post_init__(self):
        if self.slow_factor <= 1.0:
            raise ValueError(
                f"fleet.slow_eject.slow_factor must be > 1, got {self.slow_factor}")
        if self.eject_after < 1:
            raise ValueError(
                f"fleet.slow_eject.eject_after must be >= 1, got {self.eject_after}")


@dataclass(frozen=True)
class FleetObsConfig:
    """Fleet-wide observability (obs/fleet.py, docs/OBSERVABILITY.md "Fleet
    observability"): the router supervisor's /varz scrape-and-merge loop
    over every live replica (federated fleet metrics on the router's
    /metrics), the multi-window SLO burn-rate tracker over the federated
    signals, and the incident flight recorder that dumps a bounded event
    ring + fleet snapshot on ejections, deep brownout, or SLO fast-burn."""

    # scrape-merge every replica's /varz into fleet-level families
    federate: bool = True
    # scrape cadence; 0 = ride the router's poll_interval_s
    scrape_interval_s: float = 0.0
    # per-scrape /varz read bound (a wedged replica skips a tick, never
    # stalls the supervisor loop)
    scrape_timeout_s: float = 2.0
    # SLO: target tail for the signal class + the error budget (bad-request
    # fraction) the burn rate is measured against
    slo_target_p99_ms: float = 250.0
    slo_error_budget: float = 0.01
    # multi-window burn-rate alerting: fast-burn fires only when BOTH the
    # short and the long window burn past slo_fast_burn x budget rate
    slo_short_window_s: float = 30.0
    slo_long_window_s: float = 300.0
    slo_fast_burn: float = 14.0
    # incident flight recorder: event-ring capacity, dump rate limit, and
    # the brownout level that triggers a dump on the way up
    flight_recorder: bool = True
    recorder_ring: int = 256
    recorder_min_interval_s: float = 30.0
    incident_brownout_level: int = 3

    def __post_init__(self):
        if not 0.0 < self.slo_error_budget < 1.0:
            raise ValueError(
                f"fleet.obs.slo_error_budget must be in (0, 1), got {self.slo_error_budget}")
        if not 0.0 < self.slo_short_window_s < self.slo_long_window_s:
            raise ValueError(
                "fleet.obs needs 0 < slo_short_window_s < slo_long_window_s, got "
                f"{self.slo_short_window_s}/{self.slo_long_window_s}")
        if self.slo_fast_burn <= 0:
            raise ValueError(
                f"fleet.obs.slo_fast_burn must be > 0, got {self.slo_fast_burn}")
        if self.recorder_ring < 8:
            raise ValueError(
                f"fleet.obs.recorder_ring must be >= 8, got {self.recorder_ring}")


@dataclass(frozen=True)
class FleetConfig:
    """Replica fleet (cli/fleet.py + serve/router.py): N cli/serve.py
    --listen subprocesses on ephemeral ports behind one router frontend —
    weighted routing, health ejection, hedging, restart-on-exit, rolling
    restart, autoscaling. docs/SERVING.md "Fleet"."""

    # starting replica count (the autoscaler moves N inside its own bounds)
    replicas: int = 2
    # router health-poll cadence against each replica's /healthz
    poll_interval_s: float = 0.25
    # consecutive poll/dispatch failures that eject a replica from rotation
    eject_failures: int = 2
    # replicas one request may try before failing typed (transport-level
    # failures and replica-side 503s re-route; per-request verdicts do not)
    route_attempts: int = 3
    # per-dispatch client timeout (router -> replica): the READ bound
    client_timeout_s: float = 60.0
    # TCP-handshake bound, split from the read bound: a PARTITIONED host
    # drops SYNs instead of refusing, and with one shared timeout every
    # probe into a blackhole burns the full read budget. Also bounds the
    # health poll's read (healthz answers in microseconds), so a
    # blackholed replica ejects in ~eject_failures x (poll_interval +
    # connect_timeout), not 60 s. 0 = legacy single-timeout behavior.
    connect_timeout_s: float = 1.0
    # post-ejection probation: a healthy poll may not readmit an ejected
    # replica before this — a flapping link produces one bounded
    # eject/readmit cycle per cooldown instead of ping-ponging every flap
    eject_cooldown_s: float = 1.0
    # default TTL granted to /register heartbeats that name none; lease
    # expiry REMOVES the backend (fleet.lease_expirations)
    lease_ttl_s: float = 5.0
    # comma list of externally-managed replica addresses ("host:port,...")
    # to run the router tier over WITHOUT spawning anything locally (the
    # cli/fleet.py --attach sugar sets this) — the multi-host deployment
    # story: replicas live wherever they live, the router attaches to them,
    # and late arrivals join via the /register lease path
    attach: str = ""
    # restart-on-exit backoff: base doubles per consecutive crash of the
    # same slot, capped — a crash-looping replica must not spin the host
    restart_backoff_ms: float = 200.0
    restart_backoff_max_s: float = 5.0
    # how long a spawned replica may take to publish listen_addr.json
    # (includes jax import + AOT warmup) before the spawn counts as failed
    spawn_timeout_s: float = 120.0
    # per-replica jitter on the health-poll schedule, as a fraction of
    # poll_interval_s: N routers x M replicas must not phase-lock their
    # /healthz polls into a thundering herd
    poll_jitter: float = 0.2
    hedge: HedgeConfig = field(default_factory=HedgeConfig)
    autoscale: AutoscaleConfig = field(default_factory=AutoscaleConfig)
    chaos: FleetChaosConfig = field(default_factory=FleetChaosConfig)
    # gray-failure (latency-based) soft ejection of slow-but-alive replicas
    slow_eject: SlowEjectConfig = field(default_factory=SlowEjectConfig)
    # socket-level network chaos: the TCP fault proxy tier between router
    # and replicas (serve/netchaos.py; chaos mode="partition" drives it)
    netchaos: NetChaosConfig = field(default_factory=NetChaosConfig)
    # fleet-wide observability: /varz federation, SLO burn rate, and the
    # incident flight recorder (obs/fleet.py)
    obs: FleetObsConfig = field(default_factory=FleetObsConfig)


@dataclass(frozen=True)
class BrownoutConfig:
    """Graceful-degradation ladder under sustained overload
    (serve/brownout.py, docs/SERVING.md "Overload & brownout"): a controller
    thread steps L0 (healthy) -> L5 (interactive-only survival) off the
    measured signals both control loops share (serve/signals.py — windowed
    per-class p99 via registry bucket-count deltas, queue depth, breaker
    state), trading response QUALITY for interactive goodput: hedging off
    first, then fill-or-flush batching, then class shedding with
    Retry-After, then tightened deadline admission and no retries. Steps up
    fast (hold_up_s) and recovers one level per cooldown_s — asymmetric
    hysteresis, so the ladder cannot flap."""

    enable: bool = False
    interval_s: float = 0.5
    # step-UP triggers (any): windowed p99 of the signal class above
    # up_p99_ms, queue depth above up_queue_depth, or an open breaker
    up_p99_ms: float = 400.0
    up_queue_depth: float = 16.0
    # step-DOWN requires ALL below these (strictly under the up thresholds
    # — the dead band between them is the hysteresis)
    down_p99_ms: float = 100.0
    down_queue_depth: float = 2.0
    # asymmetric pacing: at most one step UP per hold_up_s (react in
    # seconds), one step DOWN per cooldown_s (recover slowly, prove each
    # restored degradation holds before the next)
    hold_up_s: float = 1.0
    cooldown_s: float = 5.0
    # deepest level the ladder may reach (5 = interactive-only survival)
    max_level: int = 5
    # the Retry-After hint on brownout-shed responses
    retry_after_s: float = 1.0
    # the class whose windowed latency histogram is the tail signal
    signal_class: str = "interactive"

    def __post_init__(self):
        if self.down_p99_ms >= self.up_p99_ms or self.down_queue_depth >= self.up_queue_depth:
            raise ValueError("serve.brownout down thresholds must sit strictly below "
                             "up thresholds (the dead band is the hysteresis)")
        if not 0 <= self.max_level <= 5:
            raise ValueError(f"serve.brownout.max_level must be in [0, 5], got {self.max_level}")
        if self.hold_up_s <= 0 or self.cooldown_s <= 0:
            raise ValueError("serve.brownout.hold_up_s/cooldown_s must be > 0")


@dataclass(frozen=True)
class QuantConfig:
    """Quantized serving (serve/quant.py, docs/SERVING.md "Quantized
    serving"): the two parity-gated rungs that shrink every transferred and
    resident serving byte. ``wire="uint8"`` ships clients' RAW pixels as u8
    — staging slots, AOT signatures, and the H2D transfer all quarter — and
    the compiled executable denormalizes on device with ``data.mean/std``
    (bitwise-identical to the f32 wire when the mean is zero; measured-delta
    gated otherwise). ``weights="int8"`` is the export-time post-training
    pass: per-output-channel symmetric int8 weights with calibration
    provenance in the bundle, refused below the top-1 agreement gate."""

    # what clients submit and what crosses H2D: "float32" (normalized
    # pixels, the historical contract) | "uint8" (raw pixels, device denorm)
    wire: str = "float32"
    # bundle weight storage at export time: "float32" | "int8"
    weights: str = "float32"
    # int8 calibration batch: calib_batches x calib_batch_size seeded
    # held-out images at data.image_size (cli/serve.py synthesizes them when
    # no dataset is wired; provenance records the source)
    calib_batches: int = 2
    calib_batch_size: int = 8
    calib_seed: int = 0
    # uint8-wire parity gate: max |logit delta| vs the f32 wire tolerated
    # when the denorm is NOT the bitwise (zero-mean) case — the backend may
    # FMA-fuse the prelude's multiply+add (~1-ulp input deltas)
    wire_atol: float = 1e-3  # yamt-lint: disable=YAMT025 — read outside the package: scripts/serve_bench.py's wire-parity gate and tests/test_quant.py consume it; the serving path itself only validates it (__post_init__)
    # int8-weight parity gate: minimum top-1 agreement with the f32 bundle
    # on the calibration batch; export REFUSES to write below it
    int8_top1_min: float = 0.98

    def __post_init__(self):
        if self.wire not in ("float32", "uint8"):
            raise ValueError(f"serve.quant.wire must be float32|uint8, got {self.wire!r}")
        if self.weights not in ("float32", "int8"):
            raise ValueError(f"serve.quant.weights must be float32|int8, got {self.weights!r}")
        if self.calib_batches < 1 or self.calib_batch_size < 1:
            raise ValueError("serve.quant.calib_batches/calib_batch_size must be >= 1")
        if self.wire_atol <= 0:
            raise ValueError(f"serve.quant.wire_atol must be > 0, got {self.wire_atol}")
        if not 0.0 < self.int8_top1_min <= 1.0:
            raise ValueError(
                f"serve.quant.int8_top1_min must be in (0, 1], got {self.int8_top1_min}")


@dataclass(frozen=True)
class FuseChunksConfig:
    """Fused multi-chunk dispatch (serve/engine.py): a request larger than
    the biggest bucket rolls its chunk loop INTO the compiled program — all
    chunks stage into one (K, bucket, S, S, 3) buffer, transfer once, and a
    lax.scan over the chunk axis serves the whole request in ONE dispatch
    (bitwise-identical to the per-chunk path; docs/SERVING.md)."""

    enable: bool = True
    # chunk-count ladder: each K gets its own AOT-warmed (bucket, size, K)
    # executable; an off-ladder chunk count decomposes greedily into ladder
    # pieces (7 chunks with ladder [2, 4] -> 4+2+1 -> 3 dispatches), worst
    # case falls back to the per-chunk path
    ladder: Sequence[int] = (2, 4)


@dataclass(frozen=True)
class OverlapConfig:
    """Overlapped staging + back-to-back dispatch (serve/engine.py,
    serve/pipeline.py): the device-resident serving steady state. The H2D
    transfer of batch N+1 overlaps compute of batch N via a fence-tracked
    pool of staging slots filled with async jax.device_put (a slot's host
    buffer is rewritten only after its last transfer is KNOWN complete), and
    a saturated bucket dispatches runs of pre-staged batches with no host
    wake-up between dispatches — the completion thread syncs only the run's
    tail (serve.dispatches_per_wakeup; docs/SERVING.md)."""

    enable: bool = True
    # host staging buffers per (bucket, size, K) key; >= max_inflight keeps
    # the fence wait (serve.slot_wait_seconds) at ~0
    staging_slots: int = 2
    # back-to-back run cap: batches the collect thread may dispatch per
    # completion wake-up on a saturated bucket (the window still bounds
    # device-side memory); 1 = per-batch wake-ups, the pre-overlap behavior
    run_max: int = 4


@dataclass(frozen=True)
class RingConfig:
    """Device-resident request ring (serve/ring.py, serve/engine.py,
    docs/SERVING.md "Device-resident ring"): R pre-staged batch slots per
    hot (model, bucket, image_size) key consumed by ONE AOT-compiled
    lax.scan dispatch per steady-state window. Host threads only feed
    slots (async device_put through the fence-tracked slot-pool idiom) and
    drain per-slot logits; an active-slot mask lets a partially-filled
    window run the same executable with padded slots' outputs discarded —
    bitwise parity with the per-batch path by construction, the same
    discipline as the fused-K scan. Engages only when the pipeline sees a
    saturated bucket worth >= min_fill of the ring; everything else rides
    the existing per-batch dispatch path."""

    enable: bool = False
    # ring depth R: pre-staged batch slots per (model, bucket, size) key;
    # one ring dispatch consumes up to R slots
    slots: int = 4
    # minimum window occupancy (staged slots / R) before the pipeline
    # commits a ring dispatch; below it the per-batch path runs instead
    min_fill: float = 0.5

    def __post_init__(self):
        if self.slots < 2:
            raise ValueError(f"serve.ring.slots must be >= 2, got {self.slots}")
        if not 0.0 < self.min_fill <= 1.0:
            raise ValueError(
                f"serve.ring.min_fill must be in (0, 1], got {self.min_fill}")


@dataclass(frozen=True)
class CascadeConfig:
    """Confidence cascade (serve/cascade.py, docs/SERVING.md "Multi-model
    zoo & cascade"): the cheap small-tier model answers every request; a
    response whose top-1 softmax margin falls below ``threshold``
    re-submits to the big tier at the ROUTER (riding the existing leg
    machinery with a distinct trace seq). Escalation preserves the
    request's remaining deadline. At millions-of-users scale this is the
    dominant serving-cost lever: most traffic never touches the big model."""

    enable: bool = False
    # zoo model names of the two tiers; both must be served by the fleet
    small: str = ""
    big: str = ""
    # escalate when top-1 softmax probability minus top-2 is below this
    threshold: float = 0.15
    # explicit X-Model requests bypass the cascade (the client asked for a
    # specific model); False forces everything through the small tier first
    respect_explicit_model: bool = True

    def __post_init__(self):
        if not 0.0 <= self.threshold <= 1.0:
            raise ValueError(
                f"serve.zoo.cascade.threshold must be in [0, 1], got {self.threshold}")
        if self.enable and (not self.small or not self.big):
            raise ValueError("serve.zoo.cascade needs both small= and big= model names")


@dataclass(frozen=True)
class ZooConfig:
    """Multi-model zoo (serve/zoo.py, docs/SERVING.md "Multi-model zoo &
    cascade"): N named InferenceBundles behind ONE multi-tenant engine —
    per-model AOT ladders keyed (model, bucket, image_size, K) over a
    SHARED staging slot pool and dispatch path, per-model admission
    quotas, an X-Model wire identity, and model-aware fleet placement
    (the lease registration advertises each replica's served set;
    cli/fleet.py spawns per-slot assignments from ``placement``)."""

    # "name=/bundle/dir,name2=/dir2" — the served set; "" = single-bundle
    # legacy serving via serve.bundle
    models: str = ""
    # model an X-Model-less request is served by; "" = first spec entry
    default: str = ""
    # fleet placement: ";"-separated slot groups of "|"-joined model names,
    # e.g. "small|big;big" = slot 0 serves both, slot 1 serves big only;
    # "" = every slot serves the full model set
    placement: str = ""
    # per-model in-system request quotas: "small=64,big=16"; unlisted
    # models are bounded only by the queue depth
    quotas: str = ""
    # per-model image-size ladders: "small=160|192,big=224"; unlisted
    # models ride serve.image_sizes
    image_sizes: str = ""
    # the confidence cascade over the zoo's small/big tiers
    cascade: CascadeConfig = field(default_factory=CascadeConfig)


@dataclass(frozen=True)
class ServeConfig:
    """Inference serving (serve/, docs/SERVING.md): export a checkpoint to a
    folded InferenceBundle and/or serve a bundle through the AOT-batched
    engine + micro-batcher via cli/serve.py."""

    # checkpoint directory to export (e.g. <log_dir>/ckpt); "" = serve only
    export_from: str = ""
    # bundle directory: export target and/or serving source
    bundle: str = ""
    # export the EMA shadow weights when the checkpoint has them (eval-on-
    # shadow semantics); falls back to live weights when EMA was off
    use_ema: bool = True
    # batch-shape ladder: each request batch pads up to the smallest bucket
    # that fits; every bucket is AOT-compiled at startup (engine warmup)
    buckets: Sequence[int] = (1, 8, 32)
    # image-size ladder for mixed-size traffic: every (bucket, size) pair is
    # AOT-warmed so a size shift hits a warm executable, not a recompile
    # cliff; () = just data.image_size (serve/engine.py)
    image_sizes: Sequence[int] = ()
    # micro-batcher: coalesce up to max_batch images or max_wait_ms linger
    max_batch: int = 32
    max_wait_ms: float = 2.0
    # pipelined serving (serve/pipeline.py): a collect/dispatch thread keeps
    # the device fed via async dispatch while a completion thread syncs —
    # continuous batching. false = legacy one-thread sync batcher
    pipelined: bool = True
    # dispatched-but-unsynced batches the pipeline may hold (2 = double
    # buffering); bounds device-side memory, backs pressure into the queue
    max_inflight: int = 2
    # bounded request queue (backpressure: submit rejects when full)
    queue_depth: int = 256
    # per-request deadline; queued-past-deadline requests are shed. 0 = none
    deadline_ms: float = 0.0
    # AOT-precompile every bucket before accepting traffic
    warmup: bool = True
    # shard each bucket over the data mesh (buckets must divide device count)
    data_parallel: bool = False
    # donate the padded input buffer to the compiled program (serve/engine.py)
    donate_input: bool = True
    # conv/matmul compute dtype for the serving forward
    compute_dtype: str = "float32"
    # cli/serve.py synthetic load: total requests (0 = export/warmup only)
    # and the number of concurrent client threads driving them
    requests: int = 0
    clients: int = 4
    # shutdown bound: stop(drain=True) fails still-unresolved requests with
    # DrainTimeout after this long instead of hanging shutdown on a wedged
    # engine. 0 = wait forever (the pre-robustness behavior)
    drain_timeout_s: float = 10.0
    # bounded LRU for OFF-ladder executables + staging buffers (on-ladder
    # entries are pinned): a size-scanning client cannot OOM the server;
    # evictions count serve.evicted_executables
    offladder_cache: int = 8
    # multi-model zoo: N named bundles behind one multi-tenant engine,
    # X-Model wire identity, model-sharded fleet placement, cascade
    zoo: ZooConfig = field(default_factory=ZooConfig)
    # quantized serving: uint8 wire + int8 weight export (parity-gated)
    quant: QuantConfig = field(default_factory=QuantConfig)
    # fused multi-chunk dispatch: whole-request inference in one dispatch
    fuse_chunks: FuseChunksConfig = field(default_factory=FuseChunksConfig)
    # overlapped staging + back-to-back dispatch: the device-resident
    # steady state (async H2D slot pool; saturated buckets dispatch runs)
    overlap: OverlapConfig = field(default_factory=OverlapConfig)
    # device-resident request ring: one lax.scan dispatch consumes a whole
    # steady-state window of pre-staged slots (opt-in; per-batch fallback)
    ring: RingConfig = field(default_factory=RingConfig)
    # HTTP front door / admission control / fault injection sub-blocks
    listen: ListenConfig = field(default_factory=ListenConfig)
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    faults: FaultsConfig = field(default_factory=FaultsConfig)
    # brownout: the graceful-degradation ladder under sustained overload
    # (consumed by cli/serve.py at the replica tier and cli/fleet.py at the
    # router tier — same controller, different actuation targets)
    brownout: BrownoutConfig = field(default_factory=BrownoutConfig)
    # replica fleet: router tier + hedging + autoscaler + replica chaos
    # (cli/fleet.py; ignored by the single-replica cli/serve.py entry point)
    fleet: FleetConfig = field(default_factory=FleetConfig)


@dataclass(frozen=True)
class DistConfig:
    # number of data-parallel shards; 0 = use all visible devices
    num_devices: int = 0
    # call jax.distributed.initialize() at startup (multi-host pods; the
    # torch.distributed.launch/env:// rendezvous equivalent, SURVEY.md §2 #12)
    multihost: bool = False
    sync_bn: bool = True
    # ZeRO-style cross-replica sharded weight update (PAPERS.md:5); optional.
    shard_optimizer: bool = False


@dataclass(frozen=True)
class Config:
    name: str = "experiment"
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    schedule: ScheduleConfig = field(default_factory=ScheduleConfig)
    ema: EMAConfig = field(default_factory=EMAConfig)
    prune: PruneConfig = field(default_factory=PruneConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    dist: DistConfig = field(default_factory=DistConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)


# ---------------------------------------------------------------------------
# dict -> dataclass with strict key checking
# ---------------------------------------------------------------------------

def _build(dc_type, data: Mapping[str, Any], path: str = ""):
    if data is None:
        data = {}  # a YAML section header with every key commented out
    if not isinstance(data, Mapping):
        raise TypeError(f"config section '{path or dc_type.__name__}' must be a mapping, got {type(data).__name__}")
    valid = {f.name: f for f in fields(dc_type)}
    unknown = set(data) - set(valid)
    if unknown:
        raise KeyError(f"unknown config key(s) {sorted(unknown)} in section '{path or 'root'}'; valid: {sorted(valid)}")
    kwargs = {}
    for name, f in valid.items():
        if name not in data:
            continue
        v = data[name]
        sub = path + "." + name if path else name
        # `from __future__ import annotations` makes f.type a string; section
        # dataclasses are dispatched by name.
        if isinstance(f.type, str) and f.type in _SECTION_TYPES:
            kwargs[name] = _build(_SECTION_TYPES[f.type], v, sub)
        else:
            kwargs[name] = _coerce(f, v, sub)
    return dc_type(**kwargs)


_SECTION_TYPES = {
    "ModelConfig": ModelConfig,
    "DataConfig": DataConfig,
    "OptimConfig": OptimConfig,
    "ScheduleConfig": ScheduleConfig,
    "EMAConfig": EMAConfig,
    "PruneConfig": PruneConfig,
    "GuardConfig": GuardConfig,
    "TrainFaultsConfig": TrainFaultsConfig,
    "TrainConfig": TrainConfig,
    "DistConfig": DistConfig,
    "ObsConfig": ObsConfig,
    "ListenConfig": ListenConfig,
    "AdmissionConfig": AdmissionConfig,
    "FaultsConfig": FaultsConfig,
    "HedgeConfig": HedgeConfig,
    "AutoscaleConfig": AutoscaleConfig,
    "FleetChaosConfig": FleetChaosConfig,
    "NetChaosConfig": NetChaosConfig,
    "SlowEjectConfig": SlowEjectConfig,
    "FleetObsConfig": FleetObsConfig,
    "FleetConfig": FleetConfig,
    "BrownoutConfig": BrownoutConfig,
    "QuantConfig": QuantConfig,
    "FuseChunksConfig": FuseChunksConfig,
    "OverlapConfig": OverlapConfig,
    "RingConfig": RingConfig,
    "CascadeConfig": CascadeConfig,
    "ZooConfig": ZooConfig,
    "ServeConfig": ServeConfig,
    "Config": Config,
}


def _coerce(f, v, path):
    # Best-effort scalar coercion so "lr=0.1" CLI overrides work. Optional
    # fields ("X | None") accept None and coerce the non-None branch;
    # None for a non-optional field is a parse-time error, not a latent crash.
    t = f.type if isinstance(f.type, str) else getattr(f.type, "__name__", "")
    optional = isinstance(t, str) and "None" in t
    if optional:
        t = t.replace("| None", "").replace("None |", "").strip()
    if v is None:
        if optional:
            return None
        raise TypeError(f"config key '{path}' is not optional; got null")
    if isinstance(v, Mapping):
        raise TypeError(f"config key '{path}' is a scalar, not a section; got mapping {dict(v)!r}")
    if t == "int":
        if isinstance(v, bool):
            raise TypeError(f"config key '{path}' expects an int; got bool {v}")
        return int(v)
    if t == "float":
        if isinstance(v, bool):
            raise TypeError(f"config key '{path}' expects a float; got bool {v}")
        return float(v)
    if t == "bool":
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes", "on")
        return bool(v)
    if t == "str":
        return str(v)
    if isinstance(v, list):
        return tuple(v)
    return v


def config_from_dict(data: Mapping[str, Any]) -> Config:
    return _build(Config, data)


def config_to_dict(cfg) -> dict:
    return dataclasses.asdict(cfg)


# ---------------------------------------------------------------------------
# CLI parsing: app:<path> + dotted overrides
# ---------------------------------------------------------------------------


def _parse_scalar(s: str):
    if s == "":
        return ""  # yaml.safe_load("") is None, but `key=` means empty string
    try:
        return yaml.safe_load(s)
    except yaml.YAMLError:
        return s


def _set_dotted(d: dict, dotted: str, value) -> None:
    keys = dotted.split(".")
    cur = d
    for k in keys[:-1]:
        cur = cur.setdefault(k, {})
        if not isinstance(cur, dict):
            raise KeyError(f"override '{dotted}': '{k}' is not a section")
    cur[keys[-1]] = value


def parse_cli(argv: Sequence[str]) -> Config:
    """Parse ``app:<yaml> [a.b=c ...]`` into a Config.

    Mirrors the reference's ``train.py app:apps/x.yml`` convention
    (SURVEY.md §1 L6) without the process-global FLAGS.
    """
    data: dict = {}
    overrides: dict = {}
    app_seen = False
    for arg in argv:
        if arg.startswith("app:"):
            if app_seen:
                raise ValueError("multiple app: arguments")
            data = load_yaml(arg[4:])
            app_seen = True
        elif "=" in arg:
            k, v = arg.split("=", 1)
            _set_dotted(overrides, k, _parse_scalar(v))
        else:
            raise ValueError(f"unrecognized argument {arg!r} (expected app:<path> or key=value)")
    # CLI overrides always win, regardless of their position relative to app:.
    return config_from_dict(_deep_merge(data, overrides))


def load_config(path: str) -> Config:
    return config_from_dict(load_yaml(path))
