"""Step health guard: survive non-finite steps instead of dying on them.

A single NaN loss — one rotten batch, one overflow in a bf16 reduction, one
cosmic-ray bit — used to kill a multi-day run at the next log boundary
(cli/train.py raised FloatingPointError). The guard turns that into a
bounded skip: the step's update is REJECTED and the pre-step TrainState
restored, on device, inside the compiled program (:func:`wrap_step_fn` —
a per-leaf ``where`` select on the step's own finiteness verdict, fused by
XLA; no extra host syncs and no second program). The step counter still
advances, so the LR schedule, data-order resume arithmetic, and the host
step counter stay aligned — the bad batch is consumed and skipped, exactly
like a corrupt record in the data pipeline.

The host half (:class:`StepGuard`) reads the per-step verdicts once per
``train.log_every`` boundary — the metrics are already synced there, so the
guard adds zero forced syncs — counts them (``train.skipped_steps`` /
``train.nonfinite_events``), and aborts with :class:`TrainHealthError`
after ``train.guard.max_skipped_steps`` total skips, dumping a
``train_health.json`` post-mortem (the watchdog hang_report.json's sibling:
bounded recovery, then a loud, attributable death instead of either a
silent crash or an unbounded NaN treadmill). ``info()`` plugs into the
stall watchdog's info providers so a hang report also shows the guard
state.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp

from ..obs.registry import get_registry

HEALTH_REPORT_NAME = "train_health.json"


class TrainHealthError(RuntimeError):
    """More non-finite steps than train.guard.max_skipped_steps tolerates —
    the run is systematically unhealthy (LR blowup, poisoned data, broken
    kernel), not transiently unlucky. train_health.json has the post-mortem."""


def wrap_step_fn(step_fn):
    """Wraps an UN-JITTED (ts, batch, rng) -> (ts, metrics) step with the
    device-side skip: when the step's loss or grad norm is non-finite, every
    TrainState field except ``step`` is rolled back to its pre-step value.
    Must wrap INSIDE the jit boundary (parallel/dp.py does) — outside it the
    donated pre-step buffers would already be gone.

    Adds a ``skipped`` metric (1.0 = this step was rejected). The verdict is
    computed from the pmean'd metrics, so every replica selects the same
    branch and replicated state stays replicated.
    """

    def guarded(ts, batch, rng):
        new_ts, metrics = step_fn(ts, batch, rng)
        ok = jnp.isfinite(metrics["loss"]) & jnp.isfinite(metrics["grad_norm"])
        rolled = jax.tree.map(lambda new, old: jnp.where(ok, new, old), new_ts, ts)
        # the step counter always advances: LR schedule, RNG folding, and the
        # resume data-order arithmetic count CONSUMED batches, not applied
        # updates
        rolled = rolled.replace(step=new_ts.step)
        metrics = dict(metrics, skipped=1.0 - ok.astype(jnp.float32))
        return rolled, metrics

    return guarded


class StepGuard:
    """Host-side accounting for the guarded step. ``observe`` stashes the
    lazy per-step ``skipped`` verdicts (device arrays — nothing syncs);
    ``check`` reads them at the log cadence, right after the metric snapshot
    already forced the same arrays, and enforces the skip bound."""

    def __init__(self, gc, log_dir: str | None, logger=None):
        self.max_skipped = int(gc.max_skipped_steps)
        self._log_dir = log_dir  # None on non-coordinator hosts: no dump
        self._logger = logger
        self._pending: list[tuple[int, object]] = []
        self.skipped_total = 0
        self.skipped_steps: list[int] = []  # recent skip step indices (bounded)

    def observe(self, step_i: int, metrics: dict) -> None:
        self._pending.append((step_i, metrics.get("skipped")))

    def check(self, step_i: int) -> None:
        """Called at the log boundary (and once at loop exit). Raises
        TrainHealthError — after dumping train_health.json — when the total
        skip count exceeds the bound."""
        pending, self._pending = self._pending, []
        bad = [s for s, v in pending if v is not None and float(v) > 0.0]
        if bad:
            reg = get_registry()
            reg.counter("train.skipped_steps").inc(len(bad))
            reg.counter("train.nonfinite_events").inc()
            self.skipped_total += len(bad)
            self.skipped_steps = (self.skipped_steps + bad)[-64:]
            if self._logger is not None:
                self._logger.log(
                    f"step guard: {len(bad)} non-finite step(s) skipped and rolled "
                    f"back at {bad} ({self.skipped_total}/{self.max_skipped} budget used)"
                )
        if self.skipped_total > self.max_skipped:
            path = self._dump(step_i)
            raise TrainHealthError(
                f"{self.skipped_total} non-finite steps exceed "
                f"train.guard.max_skipped_steps={self.max_skipped}"
                + (f"; post-mortem in {path}" if path else "")
            )

    def info(self) -> dict:
        """Watchdog info provider: guard state for hang_report.json."""
        return {
            "skipped_total": self.skipped_total,
            "max_skipped_steps": self.max_skipped,
            "recent_skipped_steps": list(self.skipped_steps),
        }

    def _dump(self, step_i: int) -> str | None:
        if not self._log_dir:
            return None
        report = {
            "reason": "non-finite step budget exceeded",
            "last_step": step_i,
            "skipped_total": self.skipped_total,
            "max_skipped_steps": self.max_skipped,
            "recent_skipped_steps": list(self.skipped_steps),
            "registry": get_registry().snapshot(),
        }
        path = os.path.join(self._log_dir, HEALTH_REPORT_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1)
            os.replace(tmp, path)
        except OSError as e:
            if self._logger is not None:
                self._logger.error(f"could not write {HEALTH_REPORT_NAME}: {e}")
            return None
        return path
