"""Losses (reference: CrossEntropyLabelSmooth in utils/optim.py, SURVEY.md §2 #7)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_label_smooth(logits: jax.Array, labels: jax.Array, smoothing: float = 0.1) -> jax.Array:
    """Mean label-smoothed cross entropy.

    Exact reference formula: target = (1-eps)*onehot + eps/K, loss =
    -sum(target * log_softmax(logits)). Computed in float32.
    """
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    smooth = -jnp.mean(logp, axis=-1)
    return jnp.mean((1.0 - smoothing) * nll + smoothing * smooth)


def topk_correct(logits: jax.Array, labels: jax.Array, ks=(1, 5)) -> dict[str, jax.Array]:
    """Counts of top-k correct predictions (summable across batches/replicas —
    the AverageMeter allreduce pattern, SURVEY.md §2 #13)."""
    out = {}
    labels = labels.astype(jnp.int32)
    max_k = max(ks)
    if max_k > logits.shape[-1]:
        raise ValueError(f"top-{max_k} with only {logits.shape[-1]} classes")
    _, pred = jax.lax.top_k(logits, max_k)  # (N, max_k)
    hit = pred == labels[:, None]
    for k in ks:
        out[f"top{k}"] = jnp.sum(hit[:, :k]).astype(jnp.float32)
    return out
