"""Measured-tuning consumption: the measurement→production loop (VERDICT r4
missing #4 / next #2).

`scripts/tpu_watch.py` adopts A/B + sweep winners into `BENCH_TUNING.json`;
until round 5 the ONLY consumer was `bench.py`, so the driver's artifact
measured the winner while real training launches stayed on the YAML
defaults until a human edited them. `train.tuning_file` closes the loop: a
production run pointed at the tuning file picks up the adopted step config
(bn_mode / remat / remat_policy / conv1x1_dot / steps_per_dispatch) and XLA
flags with provenance logged at startup.

Validation is single-sourced here — `bench.py.load_tuning` delegates to
`validate_tuning` — so the bench and the production CLI can never disagree
about what a well-formed tuning file is. Eval accuracy is immune by
construction: `train/steps.py.make_eval_step` pins bn_mode='exact' and the
stock conv lowering regardless of these knobs (ADVICE r3 #3).
"""

from __future__ import annotations

import dataclasses as dc
import json
import os
from typing import Any

# step-config keys a tuning file may carry — the single source (bench.py
# delegates here); 'flags' is env-level and handled separately
TUNING_KEYS = ("bn_mode", "remat", "remat_policy", "conv1x1_dot", "steps_per_dispatch")
# metadata keys the watcher's adoption step writes alongside the config
# (scripts/tpu_watch.py _AB_KEYS/_DISPATCH_KEYS/_FLAG_KEYS); 'provisional'
# marks a compute-family win whose parity evidence is synthetic-fixture only;
# 'contention_invalidated'/'contention_note' mark an adoption whose measured
# justification was skewed by host contention (ADVICE r5) — kept so the run
# that consumes the tuning sees the warning, not just the decision artifact
METADATA_KEYS = ("source", "steps_per_dispatch_source", "flags", "flags_source",
                 "provisional", "contention_invalidated", "contention_note")


def validate_tuning(raw: dict) -> dict[str, Any]:
    """Validated step-config subset of a BENCH_TUNING.json dict, or {} when
    no tuning keys are present (a flags-only file is the step-config
    baseline, not a winner). Raises ValueError on any malformed value —
    callers decide whether that is fatal (production CLI: yes, the user
    asked for this file) or a logged fallback (bench: never take the
    headline down over an aux artifact)."""
    from ..ops.layers import BN_MODES

    tuning = {k: raw[k] for k in TUNING_KEYS if k in raw}
    if not tuning:
        return {}
    if tuning.get("bn_mode", "exact") not in BN_MODES:
        raise ValueError(f"bn_mode must be one of {BN_MODES}")
    if tuning.get("remat_policy", "full") not in ("full", "save_conv"):
        raise ValueError("remat_policy must be 'full' or 'save_conv'")
    if not isinstance(tuning.get("remat", False), bool):
        raise ValueError("remat must be a bool")
    if not isinstance(tuning.get("conv1x1_dot", False), bool):
        raise ValueError("conv1x1_dot must be a bool")
    k = tuning.get("steps_per_dispatch", 1)
    if isinstance(k, bool) or not isinstance(k, int) or not 1 <= k <= 16:
        # bool is an int subclass: {"steps_per_dispatch": true} would
        # otherwise silently mean single-step dispatch
        raise ValueError("steps_per_dispatch must be an int in [1, 16]")
    return tuning


def apply_tuning_file(cfg):
    """Returns (cfg', provenance_lines) with cfg.train's step-config knobs
    overridden by cfg.train.tuning_file's validated contents.

    Must run BEFORE the first backend touch: a 'flags' entry is applied to
    this process's XLA_FLAGS / LIBTPU_INIT_ARGS (appended, never
    overwritten), which the backend reads exactly once at init. The tuning
    file wins over YAML/CLI values for the keys it carries — it is an
    explicit opt-in whose whole point is that measured winners reach runs
    without hand-editing YAML; the provenance lines make the effective
    config auditable from the log."""
    path = cfg.train.tuning_file
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(f"tuning file {path} must hold a JSON object")
    # strict here (unlike bench, where tuning is an aux artifact with a
    # fallback): a typoed key ('steps_per_dispach') would silently drop a
    # measured winner from the very run the user pointed at this file
    unknown = sorted(set(raw) - set(TUNING_KEYS) - set(METADATA_KEYS))
    if unknown:
        raise ValueError(f"tuning file {path} has unknown keys {unknown}; "
                         f"valid: {TUNING_KEYS + METADATA_KEYS}")
    tuning = validate_tuning(raw)
    lines = []
    if tuning:
        src = raw.get("source", "unrecorded")
        lines.append(f"tuning: {path} -> {tuning} (source: {src})")
        if raw.get("provisional"):
            # a compute-family adoption whose parity evidence is synthetic:
            # the warning must reach the operator of the run that consumes
            # the tuning, not just the decision artifact nobody re-reads
            lines.append(f"tuning: WARNING — PROVISIONAL adoption: {raw['provisional']}")
        if raw.get("contention_invalidated"):
            lines.append(
                "tuning: WARNING — CONTENTION-INVALIDATED adoption: "
                f"{raw.get('contention_note', 'measured justification was contention-skewed')}"
            )
        cfg = dc.replace(cfg, train=dc.replace(cfg.train, **tuning))
    flags = raw.get("flags", "")
    if not isinstance(flags, str):
        raise ValueError(f"flags must be a string, got {flags!r}")
    if flags:
        xla, libtpu = partition_flags(flags)
        if xla:
            os.environ["XLA_FLAGS"] = f"{os.environ.get('XLA_FLAGS', '')} {xla}".strip()
        if libtpu:
            os.environ["LIBTPU_INIT_ARGS"] = (
                f"{os.environ.get('LIBTPU_INIT_ARGS', '')} {libtpu}".strip())
        lines.append(f"tuning: flags {flags!r} -> env "
                     f"(source: {raw.get('flags_source', 'unrecorded')})")
    if not lines:
        lines.append(f"tuning: {path} carries no tuning keys; running the baseline config")
    return cfg, lines


def partition_flags(flags_str: str) -> tuple[str, str]:
    """Split a flag string into (XLA_FLAGS, LIBTPU_INIT_ARGS) halves.

    '--xla_tpu_*' flags are libtpu options: in host XLA_FLAGS they are a
    fatal 'Unknown flag' abort at backend init (measured 2026-07-30,
    PROFILE.md round 4); on PJRT TPUs libtpu consumes them from
    LIBTPU_INIT_ARGS. The full '--xla_' prefix is required so near-miss
    typos ('--xlatpu_...') fail validation instead of reaching the backend
    (ADVICE r4 #2). bench.py keeps a jax-free DUPLICATE for its supervisor
    side (importing this module pulls jax via train/__init__); the two are
    pinned identical by tests/test_tuning.py::test_partition_flags_copies_agree."""
    xla, libtpu = [], []
    for tok in flags_str.split():
        if not tok.startswith("--xla_"):
            raise ValueError(f"flag token {tok!r} does not start with --xla_")
        (libtpu if tok.startswith("--xla_tpu_") else xla).append(tok)
    return " ".join(xla), " ".join(libtpu)
