"""The jitted train/eval step (reference: train.py run_one_epoch inner loop,
SURVEY.md §3.1).

The reference's per-step sequence — forward, CE+penalty, backward, DDP
allreduce, optimizer step, LR step, EMA update — becomes ONE XLA program:
grads are pmean'd over the 'data' mesh axis inside the step (replacing NCCL
bucketed allreduce), BN stats psum via axis_name (replacing apex SyncBN), and
the EMA/LR updates are fused in (replacing the Python-side loop bodies).
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..config import Config
from ..models.specs import Network
from ..ops.layers import BN_MODES
from .ema import ema_update
from .losses import cross_entropy_label_smooth, topk_correct


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    state: Any  # BN running stats
    opt_state: Any
    ema_params: Any  # None when EMA disabled
    ema_state: Any
    masks: Any  # {} when pruning disabled; {block_idx(str): (expanded,)} else
    # adaptive rho multiplier (nas/penalty.py); None when pruning disabled.
    # Lives in TrainState so adaptation survives checkpoint/resume.
    rho_mult: Any = None


# single source of truth for the checkpoint tree layout (ckpt/manager.py and
# resume both build from this; adding a TrainState field updates every site)
TRAIN_STATE_FIELDS = ("step", "params", "state", "opt_state", "ema_params", "ema_state", "masks", "rho_mult")


def train_state_to_dict(ts: TrainState) -> dict:
    return {k: getattr(ts, k) for k in TRAIN_STATE_FIELDS}


def init_train_state(
    net: Network, cfg: Config, optimizer: optax.GradientTransformation, rng, *, with_opt: bool = True
) -> TrainState:
    """with_opt=False leaves opt_state None — the ZeRO path builds its
    sharded accumulators on the mesh instead (parallel/zero.py)."""
    params, state = net.init(rng)
    opt_state = optimizer.init(params) if with_opt else None
    # Real copies: the shadow must not alias the live buffers (aliasing breaks
    # buffer donation of the whole TrainState).
    ema_p = jax.tree.map(jnp.copy, params) if cfg.ema.enable else None
    ema_s = jax.tree.map(jnp.copy, state) if cfg.ema.enable else None
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        state=state,
        opt_state=opt_state,
        ema_params=ema_p,
        ema_state=ema_s,
        masks={},
        rho_mult=jnp.ones((), jnp.float32) if cfg.prune.enable else None,
    )


def _dtype(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]


def _check_bn_mode(cfg: Config):
    """Fail at step-build time, not first-trace time deep inside jit."""
    if cfg.train.bn_mode not in BN_MODES:
        raise ValueError(f"unknown train.bn_mode {cfg.train.bn_mode!r} (valid: {BN_MODES})")


def _input_normalizer(cfg: Config):
    """Returns prep(image) -> compute-dtype array. Under
    data.transfer_uint8 the pipeline ships raw uint8 pixels (4x less
    host->device volume; DataConfig comment has the bandwidth math) and
    THIS applies the identical f32 normalize expression the host path uses
    (pipeline._normalize) on device, where XLA fuses it into the first
    conv's input chain. f32 sub/div are exactly rounded IEEE ops, so for
    the same u8 input the two paths agree bitwise; the only path delta is
    the u8 rounding of post-augment float pixels (<=0.5/255, pinned by
    tests/test_data.py)."""
    compute_dtype = _dtype(cfg.train.compute_dtype)
    if not cfg.data.transfer_uint8:
        return lambda image: image.astype(compute_dtype)
    mean = jnp.asarray(cfg.data.mean, jnp.float32)
    std = jnp.asarray(cfg.data.std, jnp.float32)

    def prep(image):
        x = image.astype(jnp.float32) / 255.0
        return ((x - mean) / std).astype(compute_dtype)

    return prep


def make_batch_mixer(cfg: Config):
    """Mixup/CutMix as an IN-STEP device op (beyond reference parity).

    GPU codebases mix on the host dataloader; here the mix lives inside the
    jitted step — zero host cost, fused by XLA, and under shard_map each
    replica draws a decorrelated permutation of its LOCAL shard (the step
    rng already folds in the axis index, parallel/dp.py), which is the
    standard device-local mixup. Returns None when both alphas are 0, so
    disabled configs keep the exact pre-mixup program.

    mix(rng, x, labels) -> (x_mixed, labels_b, lam): per-batch lam ~
    Beta(alpha, alpha); CutMix pastes a (H*sqrt(1-lam), W*sqrt(1-lam)) box
    from the permuted batch, clipped at the borders, and returns lam
    ADJUSTED to the actual pasted area (arXiv:1905.04899 §3.1). When both
    alphas are set, each step picks one with p=0.5 (the timm convention).
    """
    m_a, c_a = cfg.optim.mixup_alpha, cfg.optim.cutmix_alpha
    if m_a < 0 or c_a < 0:
        raise ValueError(f"mixup/cutmix alphas must be >= 0, got {m_a}/{c_a}")
    if m_a == 0 and c_a == 0:
        return None

    def mix(rng, x, labels):
        r_sel, r_lam_m, r_lam_c, r_perm, r_box = jax.random.split(rng, 5)
        n, h, w = x.shape[0], x.shape[1], x.shape[2]
        perm = jax.random.permutation(r_perm, n)
        x_b, y_b = x[perm], labels[perm]

        use_cutmix = (
            jax.random.bernoulli(r_sel, 0.5)
            if (m_a > 0 and c_a > 0)
            else jnp.asarray(c_a > 0)
        )

        # mixup half
        lam_m = jax.random.beta(r_lam_m, m_a, m_a) if m_a > 0 else jnp.float32(1.0)
        x_mix = lam_m.astype(x.dtype) * x + (1.0 - lam_m).astype(x.dtype) * x_b

        # cutmix half: box centered uniformly, side = dim * sqrt(1 - lam)
        lam_c = jax.random.beta(r_lam_c, c_a, c_a) if c_a > 0 else jnp.float32(1.0)
        cut = jnp.sqrt(1.0 - lam_c)
        rh, rw = jnp.round(h * cut), jnp.round(w * cut)
        cy = jax.random.randint(r_box, (), 0, h)
        cx = jax.random.fold_in(r_box, 1)
        cx = jax.random.randint(cx, (), 0, w)
        iy = jnp.arange(h)[None, :, None, None]
        ix = jnp.arange(w)[None, None, :, None]
        in_box = (
            (iy >= cy - rh // 2) & (iy < cy + (rh + 1) // 2)
            & (ix >= cx - rw // 2) & (ix < cx + (rw + 1) // 2)
        )
        x_cut = jnp.where(in_box, x_b, x)
        # actual pasted fraction (border clipping makes it < (1-lam_c))
        frac = jnp.mean(in_box.astype(jnp.float32))
        lam_cut = 1.0 - frac

        x_out = jnp.where(use_cutmix, x_cut, x_mix)
        lam = jnp.where(use_cutmix, lam_cut, lam_m).astype(jnp.float32)
        return x_out, y_b, lam

    return mix


def make_train_step(
    net: Network,
    cfg: Config,
    optimizer: optax.GradientTransformation,
    lr_fn: Callable,
    *,
    axis_name: str | None = None,
    penalty_fn: Callable[[Any, Mapping[str, Any]], jax.Array] | None = None,
    sharded_update: Callable | None = None,
):
    """Returns step_fn(ts, batch, rng) -> (ts, metrics).

    ``penalty_fn(params, masks)`` is the AtomNAS FLOPs-weighted BN-gamma L1
    hook (SURVEY.md §3.2); None for plain training. ``batch`` is
    {'image': (N,H,W,C), 'label': (N,)} already on device.

    ``sharded_update(grads_local, opt_state_shard, params)`` replaces the
    replicated pmean+optax update with the ZeRO cross-replica sharded update
    (parallel/zero.py); it receives un-averaged local grads (the mean rides
    the psum_scatter).
    """
    compute_dtype = _dtype(cfg.train.compute_dtype)
    # dist.sync_bn=False: per-replica batch statistics in the NORMALIZATION
    # (grad allreduce still uses axis_name) — the reference's non-SyncBN DDP
    # mode. DDP broadcasts rank 0's buffers, so the updated running stats are
    # explicitly broadcast from device 0 below; without that the "replicated"
    # state would silently diverge across replicas (and across hosts).
    bn_axis = axis_name if cfg.dist.sync_bn else None

    def forward(params, state, image, masks, rng):
        imasks = {int(k): v for k, v in masks.items()} or None
        return net.apply(
            params,
            state,
            image,
            train=True,
            axis_name=bn_axis,
            compute_dtype=compute_dtype,
            masks=imasks,
            rng=rng,
            bn_mode=cfg.train.bn_mode,
            conv1x1_dot=cfg.train.conv1x1_dot,
        )

    if cfg.train.remat_policy not in ("full", "save_conv"):
        # validated even with remat off, so a config typo can't lie dormant
        # until someone flips remat on
        raise ValueError(f"unknown train.remat_policy {cfg.train.remat_policy!r}")
    _check_bn_mode(cfg)
    if cfg.train.remat:
        # recompute activations during backward: HBM for FLOPs
        # (jax.checkpoint; SURVEY.md §0 HBM-bandwidth note)
        if cfg.train.remat_policy == "full":
            forward = jax.checkpoint(forward)
        else:
            # save_conv: keep the MXU results, recompute the BN/act chains
            # (the conv_out landmark in ops/layers.py Conv2D.apply)
            forward = jax.checkpoint(
                forward, policy=jax.checkpoint_policies.save_only_these_names("conv_out")
            )

    prep_input = _input_normalizer(cfg)
    mixer = make_batch_mixer(cfg)

    def loss_fn(params, state, batch, masks, rho_mult, step, rng):
        x = prep_input(batch["image"])
        if mixer is not None:
            # distinct stream from the forward's dropout/drop-path rngs
            # (blocks fold small indices, classifier uses the raw key)
            x, label_b, lam = mixer(jax.random.fold_in(rng, 0x6D6978), x, batch["label"])
        logits, new_state = forward(params, state, x, masks, rng)
        ce = cross_entropy_label_smooth(logits, batch["label"], cfg.optim.label_smoothing)
        if mixer is not None:
            # CE is linear in the target distribution, so the convex label
            # combination IS the convex loss combination (smoothing included)
            ce = lam * ce + (1.0 - lam) * cross_entropy_label_smooth(
                logits, label_b, cfg.optim.label_smoothing)
        pen = (
            penalty_fn(params, masks, rho_mult=rho_mult, step=step)
            if penalty_fn is not None
            else jnp.zeros((), jnp.float32)
        )
        return ce + pen, (new_state, logits, ce, pen)

    def step_fn(ts: TrainState, batch, rng):
        rng = jax.random.fold_in(rng, ts.step)
        (loss, (new_state, logits, ce, pen)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            ts.params, ts.state, batch, ts.masks, ts.rho_mult, ts.step, rng
        )
        if axis_name is not None and bn_axis is None:
            # non-SyncBN mode: restore the replication invariant by
            # broadcasting device 0's updated running stats (DDP rank-0
            # buffer semantics, globally — incl. multi-host)
            idx = lax.axis_index(axis_name)
            new_state = jax.tree.map(
                lambda s: lax.psum(jnp.where(idx == 0, s, jnp.zeros_like(s)), axis_name), new_state
            )
        if sharded_update is not None:
            new_params, new_opt_state, grad_norm = sharded_update(grads, ts.opt_state, ts.params)
        else:
            if axis_name is not None:
                grads = lax.pmean(grads, axis_name)
            updates, new_opt_state = optimizer.update(grads, ts.opt_state, ts.params)
            new_params = optax.apply_updates(ts.params, updates)
            grad_norm = optax.global_norm(grads)
        new_ema_p = ema_update(cfg.ema, ts.ema_params, new_params, ts.step) if cfg.ema.enable else None
        new_ema_s = ema_update(cfg.ema, ts.ema_state, new_state, ts.step) if cfg.ema.enable else None

        correct = topk_correct(logits, batch["label"], ks=(1,))["top1"]
        n = jnp.asarray(logits.shape[0], jnp.float32)
        metrics = {
            "loss": loss,
            "ce": ce,
            "penalty": pen,
            "top1": correct / n,
            "lr": lr_fn(ts.step),
            "grad_norm": grad_norm,
            "finite": jnp.isfinite(loss).astype(jnp.float32),
        }
        if axis_name is not None:
            metrics = {k: lax.pmean(v, axis_name) for k, v in metrics.items()}
        new_ts = ts.replace(
            step=ts.step + 1,
            params=new_params,
            state=new_state,
            opt_state=new_opt_state,
            ema_params=new_ema_p,
            ema_state=new_ema_s,
        )
        return new_ts, metrics

    return step_fn


def make_eval_step(net: Network, cfg: Config, *, axis_name: str | None = None):
    """Returns eval_fn(params, state, batch, masks) -> summed metric counts
    {'top1','top5','n','loss_sum'} — allreduce-able AverageMeter counts
    (SURVEY.md §2 #13). Runs on EMA shadow weights when the caller passes
    them (reference: eval-on-shadow, SURVEY.md §2 #8).

    Perf knobs do NOT leak into the metric path (ADVICE r3 #3): eval always
    normalizes with the reference-parity exact BN expression and the stock
    conv lowering regardless of train.bn_mode/train.conv1x1_dot, so a tuned
    training config can never perturb reported accuracy. (The bn_mode
    perturbation itself is measured — on purpose, via net.apply directly —
    by test_acceptance_mbv2.py::test_full_scale_bn_mode_prediction_agreement.)"""
    # the value is ignored here (eval pins exact), but a misspelled
    # train.bn_mode must still fail fast in an eval-only run rather than
    # only when a train step is ever built (ADVICE r4 #4)
    _check_bn_mode(cfg)
    compute_dtype = _dtype(cfg.train.compute_dtype)

    prep_input = _input_normalizer(cfg)

    def eval_fn(params, state, batch, masks):
        imasks = {int(k): v for k, v in masks.items()} or None
        logits, _ = net.apply(
            params,
            state,
            prep_input(batch["image"]),
            train=False,
            compute_dtype=compute_dtype,
            masks=imasks,
            bn_mode="exact",
            conv1x1_dot=False,
        )
        labels = batch["label"]
        # padded examples carry label -1: mask them out of every count
        valid = (labels >= 0).astype(jnp.float32)
        safe_labels = jnp.maximum(labels, 0)
        k = min(5, logits.shape[-1])
        _, pred = lax.top_k(logits, k)
        hit = (pred == safe_labels[:, None]) & (valid[:, None] > 0)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, safe_labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
        metrics = {
            "top1": jnp.sum(hit[:, :1]).astype(jnp.float32),
            "top5": jnp.sum(hit).astype(jnp.float32),
            "n": jnp.sum(valid),
            "loss_sum": jnp.sum(nll * valid),
        }
        if axis_name is not None:
            metrics = {k: lax.psum(v, axis_name) for k, v in metrics.items()}
        return metrics

    return eval_fn
