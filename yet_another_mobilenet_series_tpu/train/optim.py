"""Optimizer construction (reference: utils/optim.py get_optimizer,
SURVEY.md §2 #7).

Reproduced semantics:
- TF-style RMSProp: accumulator initialized to 1.0, eps *inside* the sqrt,
  heavy-ball momentum applied after the RMS normalization — the combination
  the MNAS/MobileNet recipes assume (SURVEY.md §7 hard part 2). By default
  the momentum buffer also accumulates the LR-scaled update (TF ordering:
  ``mom = m*mom + lr*g/sqrt(nu+eps)``), which differs from torch-RMSprop's
  apply-time LR across every LR decay boundary; ``rmsprop_tf_momentum_order
  = false`` selects the torch ordering.
- Coupled L2 weight decay added to the *gradient* before the optimizer
  transform (torch ``weight_decay=`` semantics, not AdamW-decoupled).
- Per-parameter weight-decay exemptions: BN gamma/beta and biases (and
  optionally depthwise kernels) get no decay.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..config import OptimConfig


def clip_by_global_norm(max_norm: float, psum_axis: str | None = None) -> optax.GradientTransformation:
    """optax.clip_by_global_norm, but norm-aware of cross-replica sharding:
    with ``psum_axis`` the squared norm is psum'd so that clipping a ZeRO
    gradient SHARD uses the true global norm (each replica computes the same
    scale, so shards stay consistent). Same (empty) state as optax's — the
    optimizer state tree is checkpoint-compatible either way."""

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        sq = optax.global_norm(updates) ** 2
        if psum_axis is not None:
            sq = lax.psum(sq, psum_axis)
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-16))
        return jax.tree.map(lambda u: u * scale, updates), state

    return optax.GradientTransformation(init, update)


def wd_mask(params, cfg: OptimConfig):
    """True = apply weight decay. Walks the param tree by key names:
    BN params live under '*_bn'/'bn' subtrees with leaves gamma/beta; biases
    are leaves named 'b'; depthwise kernels live under 'dw*' subtrees."""

    def mask_tree(tree, path=()):
        if isinstance(tree, dict):
            return {k: mask_tree(v, path + (k,)) for k, v in tree.items()}
        leaf_name = path[-1] if path else ""
        in_bn = any(p == "bn" or p.endswith("_bn") for p in path)
        in_dw = any(p.startswith("dw") and not p.endswith("_bn") for p in path)
        if cfg.wd_skip_bn and (in_bn or leaf_name in ("gamma", "beta")):
            return False
        if cfg.wd_skip_bias and leaf_name == "b":
            return False
        if cfg.wd_skip_depthwise and in_dw:
            return False
        return True

    return mask_tree(params)


def make_optimizer(
    cfg: OptimConfig, lr_fn: Callable, params_example, *, shard_axis: str | None = None
) -> optax.GradientTransformation:
    """``shard_axis``: set to the mesh axis name when the optimizer will run
    on ZeRO gradient shards (dist.shard_optimizer) so grad clipping psums the
    true global norm instead of clipping per-shard."""
    txs = []
    if cfg.grad_clip_norm > 0:
        txs.append(clip_by_global_norm(cfg.grad_clip_norm, psum_axis=shard_axis))
    if cfg.weight_decay > 0:
        mask = wd_mask(params_example, cfg)
        txs.append(optax.add_decayed_weights(cfg.weight_decay, mask=lambda p: mask))
    lr_applied = False
    if cfg.optimizer == "rmsprop":
        # TF-style: nu0=1, update = g / sqrt(nu + eps); then momentum.
        txs.append(optax.scale_by_rms(decay=cfg.rmsprop_decay, eps=cfg.rmsprop_eps, initial_scale=1.0))
        if cfg.momentum > 0:
            if cfg.rmsprop_tf_momentum_order:
                # TF ordering: mom = m*mom + lr*g/sqrt(nu+eps) — LR scales the
                # normalized gradient BEFORE it enters the buffer, so earlier
                # contributions keep the LR of the step that produced them.
                txs.append(optax.scale_by_learning_rate(lr_fn))
                lr_applied = True
            txs.append(optax.trace(decay=cfg.momentum, nesterov=False))
    elif cfg.optimizer == "sgd":
        if cfg.momentum > 0:
            # torch SGD semantics: buf = m*buf + g; param -= lr*buf.
            txs.append(optax.trace(decay=cfg.momentum, nesterov=False))
    elif cfg.optimizer == "adamw":
        # decoupled variant kept for experimentation; wd handled above stays
        # coupled unless weight_decay==0 here.
        txs.append(optax.scale_by_adam())
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if not lr_applied:
        txs.append(optax.scale_by_learning_rate(lr_fn))
    return optax.chain(*txs)
