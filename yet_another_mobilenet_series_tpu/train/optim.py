"""Optimizer construction (reference: utils/optim.py get_optimizer,
SURVEY.md §2 #7).

Reproduced semantics:
- TF-style RMSProp: accumulator initialized to 1.0, eps *inside* the sqrt,
  heavy-ball momentum applied after the RMS normalization — the combination
  the MNAS/MobileNet recipes assume (SURVEY.md §7 hard part 2). By default
  the momentum buffer also accumulates the LR-scaled update (TF ordering:
  ``mom = m*mom + lr*g/sqrt(nu+eps)``), which differs from torch-RMSprop's
  apply-time LR across every LR decay boundary; ``rmsprop_tf_momentum_order
  = false`` selects the torch ordering.
- Coupled L2 weight decay added to the *gradient* before the optimizer
  transform (torch ``weight_decay=`` semantics, not AdamW-decoupled).
- Per-parameter weight-decay exemptions: BN gamma/beta and biases (and
  optionally depthwise kernels) get no decay.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax

from ..config import OptimConfig


def wd_mask(params, cfg: OptimConfig):
    """True = apply weight decay. Walks the param tree by key names:
    BN params live under '*_bn'/'bn' subtrees with leaves gamma/beta; biases
    are leaves named 'b'; depthwise kernels live under 'dw*' subtrees."""

    def mask_tree(tree, path=()):
        if isinstance(tree, dict):
            return {k: mask_tree(v, path + (k,)) for k, v in tree.items()}
        leaf_name = path[-1] if path else ""
        in_bn = any(p == "bn" or p.endswith("_bn") for p in path)
        in_dw = any(p.startswith("dw") and not p.endswith("_bn") for p in path)
        if cfg.wd_skip_bn and (in_bn or leaf_name in ("gamma", "beta")):
            return False
        if cfg.wd_skip_bias and leaf_name == "b":
            return False
        if cfg.wd_skip_depthwise and in_dw:
            return False
        return True

    return mask_tree(params)


def make_optimizer(cfg: OptimConfig, lr_fn: Callable, params_example) -> optax.GradientTransformation:
    txs = []
    if cfg.grad_clip_norm > 0:
        txs.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    if cfg.weight_decay > 0:
        mask = wd_mask(params_example, cfg)
        txs.append(optax.add_decayed_weights(cfg.weight_decay, mask=lambda p: mask))
    lr_applied = False
    if cfg.optimizer == "rmsprop":
        # TF-style: nu0=1, update = g / sqrt(nu + eps); then momentum.
        txs.append(optax.scale_by_rms(decay=cfg.rmsprop_decay, eps=cfg.rmsprop_eps, initial_scale=1.0))
        if cfg.momentum > 0:
            if cfg.rmsprop_tf_momentum_order:
                # TF ordering: mom = m*mom + lr*g/sqrt(nu+eps) — LR scales the
                # normalized gradient BEFORE it enters the buffer, so earlier
                # contributions keep the LR of the step that produced them.
                txs.append(optax.scale_by_learning_rate(lr_fn))
                lr_applied = True
            txs.append(optax.trace(decay=cfg.momentum, nesterov=False))
    elif cfg.optimizer == "sgd":
        if cfg.momentum > 0:
            # torch SGD semantics: buf = m*buf + g; param -= lr*buf.
            txs.append(optax.trace(decay=cfg.momentum, nesterov=False))
    elif cfg.optimizer == "adamw":
        # decoupled variant kept for experimentation; wd handled above stays
        # coupled unless weight_decay==0 here.
        txs.append(optax.scale_by_adam())
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if not lr_applied:
        txs.append(optax.scale_by_learning_rate(lr_fn))
    return optax.chain(*txs)
