"""Training mechanics: losses, optimizers, schedules, EMA, step builders."""

from .ema import ema_update
from .losses import cross_entropy_label_smooth, topk_correct
from .optim import make_optimizer, wd_mask
from .schedules import make_lr_schedule
from .steps import (
    TrainState,
    init_train_state,
    make_eval_step,
    make_train_step,
    train_state_to_dict,
)

__all__ = [
    "ema_update", "cross_entropy_label_smooth", "topk_correct",
    "make_optimizer", "wd_mask", "make_lr_schedule",
    "TrainState", "init_train_state", "make_eval_step", "make_train_step",
    "train_state_to_dict",
]
