"""Deterministic, seeded fault injection for the TRAIN data stream — the
training twin of serve/faults.py.

Every training-side recovery path added by the robustness PR — corrupt-record
skip + counting (data/pipeline.py resilient_batches), the non-finite step
rollback (train/guard.py), the loader-stall watchdog drill, and the SIGTERM
preemption checkpoint (cli/train.py) — is dead code until something actually
fails, and "yank the power" is not a unit test. :class:`FaultyTrainSource`
wraps the raw batch iterator (data/__init__.py's ``inject`` hook, UNDER the
resilience layers, so injected faults travel the exact path real ones take)
and injects on a seeded, batch-indexed schedule:

- **corrupt records** — each pull raises
  :class:`~..data.pipeline.CorruptRecordError` with probability
  ``corrupt_record_rate`` (one ``random.Random(seed)`` draw per pull,
  deterministic in pull order) — the resilience wrapper must skip and count
  it; a rate of 1.0 drills the bounded consecutive-failure abort;
- **step-NaN** — the batch served for a global step in ``nan_at_steps`` gets
  its first image poisoned with NaN, so the compiled step's loss goes
  non-finite and the guard's rollback path runs for real;
- **loader stall** — the pull for ``stall_at_step`` sleeps ``stall_ms``
  (stall-watchdog drill: a fat ``data/next`` span and, past the deadline, a
  hang report);
- **kill-at-step** — after serving ``kill_at_step``'s batch the injector
  sends THIS process a real ``SIGTERM`` (the preemption drill: the handler
  must checkpoint synchronously and exit 0 with a resume marker).

Step indexing is GLOBAL (``start_step`` offsets a resumed stream), matching
the train loop's host step counter — but note the loop prefetches
(``data.device_prefetch`` + the optional prefetch thread), so a pull-indexed
event fires up to that many steps before the loop processes the batch.
Injected events are counted (``train.faults.*``) so a chaos round's books
are auditable from the same registry snapshot as the recovery counters it
provoked.
"""

from __future__ import annotations

import os
import random
import signal
import time
from typing import Iterator

import numpy as np

from ..data.pipeline import CorruptRecordError
from ..obs.registry import get_registry


class FaultyTrainSource:
    """Iterator wrapper with a seeded train-side fault schedule; see module
    docstring for the knobs. Built from a config.TrainFaultsConfig via
    :meth:`from_config` (identity when disabled)."""

    def __init__(
        self,
        it: Iterator[dict],
        *,
        seed: int = 0,
        corrupt_record_rate: float = 0.0,
        nan_at_steps=(),
        stall_at_step: int = -1,
        stall_ms: float = 0.0,
        kill_at_step: int = -1,
        start_step: int = 0,
    ):
        self._it = iter(it)
        self._rng = random.Random(seed)
        self._corrupt_rate = float(corrupt_record_rate)
        self._nan_at = {int(s) for s in nan_at_steps}
        self._stall_at = int(stall_at_step)
        self._stall_s = float(stall_ms) / 1e3
        self._kill_at = int(kill_at_step)
        self._step = int(start_step)  # next global step to be served
        self._reg = get_registry()

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        # one seeded draw per PULL (not per served batch): a skipped corrupt
        # pull consumes schedule position, deterministic in pull order
        if self._corrupt_rate > 0 and self._rng.random() < self._corrupt_rate:
            self._reg.counter("train.faults.corrupt_records").inc()
            raise CorruptRecordError("injected corrupt record (train.faults)")
        step = self._step
        if step == self._stall_at and self._stall_s > 0:
            self._reg.counter("train.faults.stalls").inc()
            time.sleep(self._stall_s)
        batch = next(self._it)
        if step in self._nan_at:
            self._reg.counter("train.faults.nan_steps").inc()
            image = np.array(batch["image"], dtype=np.float32, copy=True)
            image[0] = np.nan
            batch = dict(batch, image=image)
        self._step = step + 1
        if step == self._kill_at:
            self._reg.counter("train.faults.kills").inc()
            os.kill(os.getpid(), signal.SIGTERM)
        return batch

    @classmethod
    def from_config(cls, it, fc, start_step: int = 0):
        """Wrap per a config.TrainFaultsConfig block; identity when disabled."""
        if not fc.enable:
            return it
        return cls(
            it,
            seed=fc.seed,
            corrupt_record_rate=fc.corrupt_record_rate,
            nan_at_steps=fc.nan_at_steps,
            stall_at_step=fc.stall_at_step,
            stall_ms=fc.stall_ms,
            kill_at_step=fc.kill_at_step,
            start_step=start_step,
        )
