"""Exponential moving average of params + BN stats (reference:
ExponentialMovingAverage in utils/optim.py, SURVEY.md §2 #8).

Shadow = decay * shadow + (1-decay) * value, maintained *inside* the jitted
train step; eval runs on the shadow copy. With ``warmup`` the effective decay
is min(decay, (1+t)/(10+t)) — the TF convention that stops early steps from
being dominated by random init.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import EMAConfig


def ema_update(cfg: EMAConfig, shadow, value, step):
    """One EMA step. ``shadow``/``value`` are matching pytrees (params and BN
    state are both tracked, like the reference's param+buffer EMA)."""
    if not cfg.enable:
        return shadow
    decay = jnp.asarray(cfg.decay, jnp.float32)
    if cfg.warmup:
        t = jnp.asarray(step, jnp.float32)
        decay = jnp.minimum(decay, (1.0 + t) / (10.0 + t))
    return jax.tree.map(lambda s, v: s * decay + (1.0 - decay) * v.astype(s.dtype), shadow, value)
