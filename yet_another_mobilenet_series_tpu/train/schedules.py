"""LR schedules, stepped per iteration (reference: utils/optim.py
get_lr_scheduler — linear warmup + MNAS-style staircase exponential decay, or
cosine; SURVEY.md §2 #9)."""

from __future__ import annotations

import jax.numpy as jnp

from ..config import ScheduleConfig


def make_lr_schedule(cfg: ScheduleConfig, total_batch: int, steps_per_epoch: int, total_epochs: float):
    """Returns lr(step) -> float32 scalar, usable inside jit."""
    base_lr = cfg.base_lr * (total_batch / 256.0) if cfg.scale_by_batch else cfg.base_lr
    warmup_steps = max(int(cfg.warmup_epochs * steps_per_epoch), 0)
    total_steps = max(int(total_epochs * steps_per_epoch), warmup_steps + 1)

    if cfg.schedule == "exp_decay":
        decay_steps = max(int(cfg.decay_epochs * steps_per_epoch), 1)

        def lr_fn(step):
            step = jnp.asarray(step, jnp.float32)
            warm = base_lr * step / jnp.maximum(warmup_steps, 1)
            n_decays = jnp.floor(jnp.maximum(step - warmup_steps, 0.0) / decay_steps)
            decayed = base_lr * jnp.power(cfg.decay_rate, n_decays)
            return jnp.where(step < warmup_steps, warm, decayed).astype(jnp.float32)

    elif cfg.schedule == "cosine":

        def lr_fn(step):
            step = jnp.asarray(step, jnp.float32)
            warm = base_lr * step / jnp.maximum(warmup_steps, 1)
            t = jnp.clip((step - warmup_steps) / jnp.maximum(total_steps - warmup_steps, 1), 0.0, 1.0)
            cos = 0.5 * (1.0 + jnp.cos(jnp.pi * t))
            floor = cfg.final_lr_factor * base_lr
            return jnp.where(step < warmup_steps, warm, floor + (base_lr - floor) * cos).astype(jnp.float32)

    elif cfg.schedule == "constant":

        def lr_fn(step):
            step = jnp.asarray(step, jnp.float32)
            warm = base_lr * step / jnp.maximum(warmup_steps, 1)
            return jnp.where(step < warmup_steps, warm, base_lr).astype(jnp.float32)

    else:
        raise ValueError(f"unknown schedule {cfg.schedule!r}")

    return lr_fn
