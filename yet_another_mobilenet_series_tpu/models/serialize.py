"""Exact Network <-> JSON-able dict serialization.

The AtomNAS resume path must rebuild the model *at the pruned shape* before
weights can load (reference: checkpoint carries the live block-spec,
SURVEY.md §3.5). Rather than round-tripping through the ratio-based stage
grammar (lossy for pruned group sizes), the live ``Network`` spec tree is
serialized field-for-field; the searched final architecture is emitted in the
same form.
"""

from __future__ import annotations

from typing import Any

from ..ops.blocks import ConvBNAct, InvertedResidual
from ..ops.layers import Dense
from .specs import Network

# v2 adds the ``inference`` marker: True means the weight tree next to the
# spec is a FOLDED serving artifact (BN running stats + affine baked into the
# adjacent conv weights, serve/export.py) and must never be resumed into
# training. v1 dicts (no marker) keep loading — every pre-serving checkpoint
# sidecar and searched_arch.json in the wild is v1.
_SCHEMA_VERSION = 2


def spec_is_inference(d: dict[str, Any]) -> bool:
    """True when ``d`` (a network_to_dict payload) marks a folded serving
    bundle. v1 payloads predate serving and are always training-shaped."""
    return bool(d.get("inference", False))


def _conv_bn_act_to_dict(s: ConvBNAct) -> dict:
    return {
        "in_channels": s.in_channels,
        "out_channels": s.out_channels,
        "kernel_size": s.kernel_size,
        "stride": s.stride,
        "groups": s.groups,
        "active_fn": s.active_fn,
        "bn_momentum": s.bn_momentum,
        "bn_eps": s.bn_eps,
    }


def _block_to_dict(b: InvertedResidual) -> dict:
    return {
        "in_channels": b.in_channels,
        "out_channels": b.out_channels,
        "expanded_channels": b.expanded_channels,
        "stride": b.stride,
        "kernel_sizes": list(b.kernel_sizes),
        "group_channels": list(b.group_channels),
        "active_fn": b.active_fn,
        "se_channels": b.se_channels,
        "se_gate_fn": b.se_gate_fn,
        "se_inner_act": b.se_inner_act,
        "bn_momentum": b.bn_momentum,
        "bn_eps": b.bn_eps,
        "project_act": b.project_act,
        "allow_residual": b.allow_residual,
        "force_expand": b.force_expand,
        "drop_path": b.drop_path,
    }


def _dense_to_dict(d: Dense) -> dict:
    return {"in_features": d.in_features, "out_features": d.out_features, "use_bias": d.use_bias, "init_std": d.init_std}


def network_to_dict(net: Network, *, inference: bool = False) -> dict[str, Any]:
    return {
        "schema": _SCHEMA_VERSION,
        "inference": inference,
        "stem": _conv_bn_act_to_dict(net.stem),
        "blocks": [_block_to_dict(b) for b in net.blocks],
        "head": _conv_bn_act_to_dict(net.head) if net.head is not None else None,
        "feature": _dense_to_dict(net.feature) if net.feature is not None else None,
        "feature_act": net.feature_act,
        "classifier": _dense_to_dict(net.classifier),
        "dropout": net.dropout,
        "image_size": net.image_size,
    }


def network_from_dict(d: dict[str, Any]) -> Network:
    # v1 payloads are a strict subset of v2 (no "inference" marker): the spec
    # fields are identical, so the read path accepts both.
    if d.get("schema") not in (1, _SCHEMA_VERSION):
        raise ValueError(f"unsupported network schema {d.get('schema')!r}")

    def _blk(bd):
        bd = dict(bd)
        bd["kernel_sizes"] = tuple(bd["kernel_sizes"])
        bd["group_channels"] = tuple(bd["group_channels"])
        return InvertedResidual(**bd)

    return Network(
        stem=ConvBNAct(**d["stem"]),
        blocks=tuple(_blk(b) for b in d["blocks"]),
        head=ConvBNAct(**d["head"]) if d["head"] is not None else None,
        feature=Dense(**d["feature"]) if d["feature"] is not None else None,
        feature_act=d["feature_act"],
        classifier=Dense(**d["classifier"]),
        dropout=d["dropout"],
        image_size=d["image_size"],
    )
