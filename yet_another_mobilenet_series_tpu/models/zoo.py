"""Named architectures (reference: models/mobilenet_v1|v2|v3.py + MNASNet +
the AtomNAS supernet block-specs in apps/*.yml — SURVEY.md §2 #4-5).

Tables are transcribed from the public papers:
- MobileNetV1 (arXiv:1704.04861 Table 1)
- MobileNetV2 (arXiv:1801.04381 Table 2)
- MobileNetV3-Large/Small (arXiv:1905.02244 Tables 1-2)
- MNASNet-A1 (arXiv:1807.11626 Fig. 7)
- AtomNAS supernet (arXiv:1912.09640 §3: MobileNetV2-skeleton with each
  MBConv's expanded channels split into k=3/5/7 atomic groups)
- EfficientNet-B0 / Lite0 (arXiv:1905.11946 Table 1; beyond reference
  parity — same MNASNet search-space lineage, expressed in the same spec
  grammar: SE=0.25 of block INPUT width with sigmoid gate and swish inner
  FC, swish everywhere; Lite drops SE and uses ReLU6 for int8 friendliness)

Golden param/MAC counts are locked in tests/test_models.py.
"""

from __future__ import annotations

from .specs import ArchDef

# --- MobileNetV1: depthwise-separable stacks, ReLU throughout ---------------
MOBILENET_V1 = ArchDef(
    stem_channels=32,
    block_specs=(
        dict(block="ds_act", c=64, n=1, s=1),
        dict(block="ds_act", c=128, n=1, s=2),
        dict(block="ds_act", c=128, n=1, s=1),
        dict(block="ds_act", c=256, n=1, s=2),
        dict(block="ds_act", c=256, n=1, s=1),
        dict(block="ds_act", c=512, n=1, s=2),
        dict(block="ds_act", c=512, n=5, s=1),
        dict(block="ds_act", c=1024, n=1, s=2),
        dict(block="ds_act", c=1024, n=1, s=1),
    ),
    head_channels=0,
    stem_act="relu",
    default_act="relu",
)

# --- MobileNetV2 (t, c, n, s), ReLU6, head 1280 -----------------------------
MOBILENET_V2 = ArchDef(
    stem_channels=32,
    block_specs=(
        dict(t=1, c=16, n=1, s=1),
        dict(t=6, c=24, n=2, s=2),
        dict(t=6, c=32, n=3, s=2),
        dict(t=6, c=64, n=4, s=2),
        dict(t=6, c=96, n=3, s=1),
        dict(t=6, c=160, n=3, s=2),
        dict(t=6, c=320, n=1, s=1),
    ),
    head_channels=1280,
    stem_act="relu6",
    head_act="relu6",
    default_act="relu6",
)

# --- MobileNetV3-Large: per-block rows (exp absolute), SE on expanded/4 -----
MOBILENET_V3_LARGE = ArchDef(
    stem_channels=16,
    block_specs=(
        dict(exp=16, c=16, n=1, s=1, k=3, act="relu"),
        dict(exp=64, c=24, n=1, s=2, k=3, act="relu"),
        dict(exp=72, c=24, n=1, s=1, k=3, act="relu"),
        dict(exp=72, c=40, n=1, s=2, k=5, act="relu", se=0.25),
        dict(exp=120, c=40, n=1, s=1, k=5, act="relu", se=0.25),
        dict(exp=120, c=40, n=1, s=1, k=5, act="relu", se=0.25),
        dict(exp=240, c=80, n=1, s=2, k=3, act="hswish"),
        dict(exp=200, c=80, n=1, s=1, k=3, act="hswish"),
        dict(exp=184, c=80, n=1, s=1, k=3, act="hswish"),
        dict(exp=184, c=80, n=1, s=1, k=3, act="hswish"),
        dict(exp=480, c=112, n=1, s=1, k=3, act="hswish", se=0.25),
        dict(exp=672, c=112, n=1, s=1, k=3, act="hswish", se=0.25),
        dict(exp=672, c=160, n=1, s=2, k=5, act="hswish", se=0.25),
        dict(exp=960, c=160, n=1, s=1, k=5, act="hswish", se=0.25),
        dict(exp=960, c=160, n=1, s=1, k=5, act="hswish", se=0.25),
    ),
    head_channels=960,
    feature_channels=1280,
    stem_act="hswish",
    head_act="hswish",
    feature_act="hswish",
    default_act="hswish",
    default_se_mode="expand",
    default_se_gate="hsigmoid",
    head_scales_down=True,
)

# --- MobileNetV3-Small --------------------------------------------------------
MOBILENET_V3_SMALL = ArchDef(
    stem_channels=16,
    block_specs=(
        dict(exp=16, c=16, n=1, s=2, k=3, act="relu", se=0.25),
        dict(exp=72, c=24, n=1, s=2, k=3, act="relu"),
        dict(exp=88, c=24, n=1, s=1, k=3, act="relu"),
        dict(exp=96, c=40, n=1, s=2, k=5, act="hswish", se=0.25),
        dict(exp=240, c=40, n=1, s=1, k=5, act="hswish", se=0.25),
        dict(exp=240, c=40, n=1, s=1, k=5, act="hswish", se=0.25),
        dict(exp=120, c=48, n=1, s=1, k=5, act="hswish", se=0.25),
        dict(exp=144, c=48, n=1, s=1, k=5, act="hswish", se=0.25),
        dict(exp=288, c=96, n=1, s=2, k=5, act="hswish", se=0.25),
        dict(exp=576, c=96, n=1, s=1, k=5, act="hswish", se=0.25),
        dict(exp=576, c=96, n=1, s=1, k=5, act="hswish", se=0.25),
    ),
    head_channels=576,
    feature_channels=1024,
    stem_act="hswish",
    head_act="hswish",
    feature_act="hswish",
    default_act="hswish",
    head_scales_down=True,
)

# --- MNASNet-A1: sepconv stem block + SE(0.25 of input) gated by sigmoid ----
MNASNET_A1 = ArchDef(
    stem_channels=32,
    block_specs=(
        dict(block="ds", c=16, n=1, s=1, k=3),
        dict(t=6, c=24, n=2, s=2, k=3),
        dict(t=3, c=40, n=3, s=2, k=5, se=0.25),
        dict(t=6, c=80, n=4, s=2, k=3),
        dict(t=6, c=112, n=2, s=1, k=3, se=0.25),
        dict(t=6, c=160, n=3, s=2, k=5, se=0.25),
        dict(t=6, c=320, n=1, s=1, k=3),
    ),
    head_channels=1280,
    stem_act="relu",
    head_act="relu",
    default_act="relu",
    default_se_mode="input",
    default_se_gate="sigmoid",
)

# --- AtomNAS supernet: MBV2 skeleton, every MBConv split into k=3/5/7 atoms -
_ATOMNAS_SPECS = (
    dict(t=1, c=16, n=1, s=1, k=[3, 5, 7]),
    dict(t=6, c=24, n=2, s=2, k=[3, 5, 7]),
    dict(t=6, c=32, n=3, s=2, k=[3, 5, 7]),
    dict(t=6, c=64, n=4, s=2, k=[3, 5, 7]),
    dict(t=6, c=96, n=3, s=1, k=[3, 5, 7]),
    dict(t=6, c=160, n=3, s=2, k=[3, 5, 7]),
    dict(t=6, c=320, n=1, s=1, k=[3, 5, 7]),
)

ATOMNAS_SUPERNET = ArchDef(
    stem_channels=32,
    block_specs=_ATOMNAS_SPECS,
    head_channels=1280,
    stem_act="relu6",
    head_act="relu6",
    default_act="relu6",
)

# "+" variants (AtomNAS-A+/B+/C+): SE everywhere + swish (SURVEY.md §6).
ATOMNAS_SUPERNET_SE = ArchDef(
    stem_channels=32,
    block_specs=tuple(dict(s, se=0.25) for s in _ATOMNAS_SPECS),
    head_channels=1280,
    stem_act="swish",
    head_act="swish",
    default_act="swish",
    default_se_mode="expand",
    default_se_gate="sigmoid",
)

# --- EfficientNet-B0: MNASNet-style stages, swish + input-mode SE -----------
_EFFICIENTNET_B0_SPECS = (
    dict(t=1, c=16, n=1, s=1, k=3),
    dict(t=6, c=24, n=2, s=2, k=3),
    dict(t=6, c=40, n=2, s=2, k=5),
    dict(t=6, c=80, n=3, s=2, k=3),
    dict(t=6, c=112, n=3, s=1, k=5),
    dict(t=6, c=192, n=4, s=2, k=5),
    dict(t=6, c=320, n=1, s=1, k=3),
)

EFFICIENTNET_B0 = ArchDef(
    stem_channels=32,
    block_specs=tuple(dict(s, se=0.25) for s in _EFFICIENTNET_B0_SPECS),
    head_channels=1280,
    stem_act="swish",
    head_act="swish",
    default_act="swish",
    default_se_mode="input",
    default_se_gate="sigmoid",
    default_se_inner="swish",
    # EfficientNet round_filters scales EVERY width incl. the head at wm<1
    # (unlike the MBV2/V3 head-never-shrinks convention).
    head_scales_down=True,
    drop_connect=0.2,  # stochastic-depth max rate, paper default
)

# Lite0: SE removed, ReLU6 everywhere (quantization-friendly). At width 1.0
# this is exact; the lite papers also pin stem/head widths across width
# multipliers — reproduce that at other widths with explicit
# model.stem_channels=32 model.head_channels=1280 overrides (exact_channels).
EFFICIENTNET_LITE0 = ArchDef(
    stem_channels=32,
    block_specs=_EFFICIENTNET_B0_SPECS,
    head_channels=1280,
    stem_act="relu6",
    head_act="relu6",
    default_act="relu6",
    drop_connect=0.2,  # the official lite recipe keeps B0's stochastic depth
)

ARCHS: dict[str, ArchDef] = {
    "mobilenet_v1": MOBILENET_V1,
    "mobilenet_v2": MOBILENET_V2,
    "mobilenet_v3_large": MOBILENET_V3_LARGE,
    "mobilenet_v3_small": MOBILENET_V3_SMALL,
    "mnasnet_a1": MNASNET_A1,
    "atomnas_supernet": ATOMNAS_SUPERNET,
    "atomnas_supernet_se": ATOMNAS_SUPERNET_SE,
    "efficientnet_b0": EFFICIENTNET_B0,
    "efficientnet_lite0": EFFICIENTNET_LITE0,
}


def get_arch(name: str) -> ArchDef:
    try:
        return ARCHS[name]
    except KeyError:
        raise ValueError(f"unknown arch {name!r}; known: {sorted(ARCHS)}") from None
