"""Block-spec grammar: YAML-expressible architecture descriptions.

Reference behavior (SURVEY.md §2 #4-5, §3.4): every model — including searched
AtomNAS results — is a list of stage specs (t/exp, c, n, s, k, act, SE) plus
stem/head widths, scaled by a width multiplier with ``make_divisible`` channel
rounding. This module turns such a list into a concrete ``Network`` of ops
specs; it is the "single most important behavioral contract" called out in
SURVEY.md §3.4.

Spec dict keys (one dict per *stage*, expanded to ``n`` blocks):

- ``block``: 'mbconv' (default) | 'ds' (depthwise-separable, V1/MNASNet stem)
- ``t``: expansion ratio (hidden = make_divisible(c_in * t)), OR
  ``exp``: absolute expanded width pre-width-mult (MobileNetV3 tables give
  these explicitly and they are NOT exact multiples of the input width)
- ``c``: output channels pre-width-mult; ``n``: repeats; ``s``: stride of the
  first block in the stage
- ``k``: kernel size or list of kernel sizes — a list splits the expanded
  channels into equal atomic groups per kernel (AtomNAS supernet)
- ``act``: activation name (defaults to the model-wide ``active_fn``)
- ``se``: squeeze-excite ratio, 0 = off
- ``se_mode``: 'expand' (MobileNetV3: se = make_divisible(ratio * expanded))
  or 'input' (MNASNet: se = max(1, int(ratio * c_in)))
- ``se_gate``: gate activation ('hsigmoid' V3-style, 'sigmoid' MNAS-style)
- ``se_inner``: activation between the SE reduce/expand FCs ('relu' V3/MNAS
  convention; 'swish' for EfficientNet-family specs)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

from ..ops.blocks import ConvBNAct, InvertedResidual
from ..ops.layers import Dense, make_divisible


@dataclass(frozen=True)
class ArchDef:
    """A named architecture: stem/stages/head pre-width-mult."""

    stem_channels: int
    block_specs: tuple[Mapping[str, Any], ...]
    head_channels: int  # 0 = classifier directly on last block output
    feature_channels: int = 0  # V3's post-pool FC width (0 = none)
    stem_act: str = "relu6"
    head_act: str = "relu6"
    feature_act: str = "hswish"
    default_act: str = "relu6"
    default_se_mode: str = "expand"
    default_se_gate: str = "hsigmoid"
    default_se_inner: str = "relu"
    # Stochastic-depth max rate (EfficientNet drop_connect, 0 = off). Per
    # block the rate ramps linearly with depth: rate_i = drop_connect * i / n
    # over the n MBConv blocks (the official EfficientNet schedule; the first
    # block is never dropped).
    drop_connect: float = 0.0
    # MBV2/V3 convention: head width does not shrink below its 1.0x value.
    head_scales_down: bool = False


@dataclass(frozen=True)
class Network:
    """A fully-resolved model: static spec tree with init/apply.

    Block params live under ``blocks/<i>``; masks (AtomNAS) are a dict
    ``{block_index: (expanded,) array}`` applied inside each block.
    """

    stem: ConvBNAct
    blocks: tuple[InvertedResidual, ...]
    head: ConvBNAct | None
    feature: Dense | None
    feature_act: str
    classifier: Dense
    dropout: float = 0.0
    image_size: int = 224  # nominal profiling resolution

    def init(self, key):
        import jax

        keys = jax.random.split(key, len(self.blocks) + 4)
        params: dict = {}
        state: dict = {}
        params["stem"], state["stem"] = self.stem.init(keys[0])
        bp, bs = {}, {}
        for i, blk in enumerate(self.blocks):
            bp[str(i)], bs[str(i)] = blk.init(keys[1 + i])
        params["blocks"], state["blocks"] = bp, bs
        if self.head is not None:
            params["head"], state["head"] = self.head.init(keys[-3])
        if self.feature is not None:
            params["feature"] = self.feature.init(keys[-2])
        params["classifier"] = self.classifier.init(keys[-1])
        return params, state

    def apply(
        self,
        params,
        state,
        x,
        *,
        train: bool,
        axis_name: str | None = None,
        compute_dtype=None,
        masks: Mapping[int, Any] | None = None,
        rng=None,
        bn_mode: str = "exact",
        conv1x1_dot: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        from ..ops.activations import get_activation
        from ..ops.layers import dropout as dropout_fn
        from ..ops.layers import global_avg_pool

        compute_dtype = compute_dtype or jnp.float32
        new_state: dict = {}
        h = x
        h, new_state["stem"] = self.stem.apply(
            params["stem"], state["stem"], h, train=train, axis_name=axis_name, compute_dtype=compute_dtype,
            bn_mode=bn_mode,
        )
        nbs: dict = {}
        # Per-block stochastic-depth streams fold the block index into the
        # step rng; the classifier dropout below keeps the UNfolded rng, and
        # rate-0 blocks skip the fold entirely, so rate-0 networks (every
        # non-EfficientNet arch) are bit-identical to the pre-drop-path code.
        need_block_rng = rng is not None and train
        for i, blk in enumerate(self.blocks):
            mask = None if masks is None else masks.get(i)
            h, nbs[str(i)] = blk.apply(
                params["blocks"][str(i)],
                state["blocks"][str(i)],
                h,
                train=train,
                axis_name=axis_name,
                compute_dtype=compute_dtype,
                mask=mask,
                bn_mode=bn_mode,
                conv1x1_dot=conv1x1_dot,
                rng=jax.random.fold_in(rng, i) if need_block_rng and blk.drop_path > 0 else None,
            )
        new_state["blocks"] = nbs
        if self.head is not None:
            h, new_state["head"] = self.head.apply(
                params["head"], state["head"], h, train=train, axis_name=axis_name, compute_dtype=compute_dtype,
                bn_mode=bn_mode, conv1x1_dot=conv1x1_dot,
            )
        h = global_avg_pool(h)  # (N, C)
        if self.feature is not None:
            h = self.feature.apply(params["feature"], h, compute_dtype=compute_dtype)
            h = get_activation(self.feature_act)(h)
        if self.dropout and train:
            h = dropout_fn(rng, h, self.dropout, train)
        logits = self.classifier.apply(params["classifier"], h.astype(jnp.float32))
        return logits, new_state


def _split_groups(expanded: int, kernels: Sequence[int]) -> tuple[int, ...]:
    """Split expanded channels into one atomic group per kernel size.

    Equal split; the remainder goes to the first (smallest-kernel) groups so
    the sum is exact and every group is non-empty.
    """
    n = len(kernels)
    base = expanded // n
    rem = expanded - base * n
    groups = tuple(base + (1 if i < rem else 0) for i in range(n))
    if any(g <= 0 for g in groups):
        raise ValueError(f"expanded={expanded} too small for {n} kernel groups")
    return groups


def build_network(
    arch: ArchDef,
    *,
    width_mult: float = 1.0,
    num_classes: int = 1000,
    dropout: float = 0.2,
    bn_momentum: float = 0.1,
    bn_eps: float = 1e-5,
    image_size: int = 224,
    block_specs_override: Sequence[Mapping[str, Any]] | None = None,
    exact_channels: Mapping[str, int] | None = None,
    drop_connect: float | None = None,
) -> Network:
    """exact_channels pins {'stem','head','feature'} widths to FINAL values,
    exempt from width_mult scaling — an explicit ``model.head_channels: 1280``
    means 1280, not make_divisible(1280*width_mult) (the AtomNAS-C 1.1x seed
    needs a widened prunable trunk under an unscaled, unprunable head)."""
    specs = tuple(block_specs_override) if block_specs_override is not None else arch.block_specs
    exact = dict(exact_channels or {})
    if unknown := set(exact) - {"stem", "head", "feature"}:
        raise ValueError(f"unknown exact_channels key(s) {sorted(unknown)}; valid: stem, head, feature")

    stem_ch = exact["stem"] if "stem" in exact else make_divisible(arch.stem_channels * width_mult)
    stem = ConvBNAct(3, stem_ch, 3, 2, active_fn=arch.stem_act, bn_momentum=bn_momentum, bn_eps=bn_eps)

    dc_rate = arch.drop_connect if drop_connect is None else drop_connect
    if not 0.0 <= dc_rate < 1.0:
        raise ValueError(f"drop_connect must be in [0, 1), got {dc_rate}")
    total_blocks = sum(int(s.get("n", 1)) for s in specs)
    block_idx = 0
    blocks: list[InvertedResidual] = []
    c_in = stem_ch
    for spec in specs:
        spec = dict(spec)
        block_type = spec.get("block", "mbconv")
        n = int(spec.get("n", 1))
        c = make_divisible(spec["c"] * width_mult)
        s = int(spec.get("s", 1))
        kernels = spec.get("k", 3)
        if isinstance(kernels, int):
            kernels = (kernels,)
        kernels = tuple(int(k) for k in kernels)
        act = spec.get("act") or arch.default_act
        se_ratio = float(spec.get("se", 0.0) or 0.0)
        se_mode = spec.get("se_mode", arch.default_se_mode)
        se_gate = spec.get("se_gate", arch.default_se_gate)
        se_inner = spec.get("se_inner", arch.default_se_inner)
        for j in range(n):
            stride = s if j == 0 else 1
            if block_type in ("ds", "ds_act"):
                expanded = c_in
            elif "exp" in spec:
                # absolute expanded width (MobileNetV3 tables); only the
                # stage's first block uses it verbatim — repeats re-derive
                # from their own input if given as ratio, but V3 lists every
                # block as its own stage so this path is exact.
                expanded = make_divisible(float(spec["exp"]) * width_mult)
            else:
                expanded = make_divisible(c_in * float(spec["t"]))
            if se_ratio > 0:
                if se_mode == "expand":
                    se_ch = make_divisible(expanded * se_ratio)
                elif se_mode == "input":
                    se_ch = max(1, int(c_in * se_ratio))
                else:
                    raise ValueError(f"unknown se_mode {se_mode!r}")
            else:
                se_ch = 0
            blocks.append(
                InvertedResidual(
                    in_channels=c_in,
                    out_channels=c,
                    expanded_channels=expanded,
                    stride=stride,
                    kernel_sizes=kernels,
                    group_channels=_split_groups(expanded, kernels),
                    active_fn=act,
                    se_channels=se_ch,
                    se_gate_fn=se_gate,
                    se_inner_act=se_inner,
                    bn_momentum=bn_momentum,
                    bn_eps=bn_eps,
                    project_act=act if block_type == "ds_act" else "identity",
                    allow_residual=block_type not in ("ds", "ds_act"),
                    drop_path=dc_rate * block_idx / total_blocks,
                )
            )
            block_idx += 1
            c_in = c

    # membership (not truthiness) so an explicit override of 0 keeps the
    # documented "0 = no head/feature layer" semantics
    if "head" in exact:
        head_ch = exact["head"]
    elif arch.head_channels:
        hc = arch.head_channels
        scaled = make_divisible(hc * width_mult)
        head_ch = scaled if (arch.head_scales_down or width_mult > 1.0) else max(hc, scaled)
    else:
        head_ch = 0
    head = None
    head_out = c_in
    if head_ch:
        head = ConvBNAct(c_in, head_ch, 1, 1, active_fn=arch.head_act, bn_momentum=bn_momentum, bn_eps=bn_eps)
        head_out = head_ch

    if "feature" in exact:
        feat_ch = exact["feature"]
    elif arch.feature_channels:
        fc = arch.feature_channels
        feat_ch = make_divisible(fc * width_mult) if width_mult > 1.0 else fc
    else:
        feat_ch = 0
    feature = None
    feat_out = head_out
    if feat_ch:
        feature = Dense(head_out, feat_ch, use_bias=True)
        feat_out = feat_ch

    classifier = Dense(feat_out, num_classes, use_bias=True)
    return Network(
        stem=stem,
        blocks=tuple(blocks),
        head=head,
        feature=feature,
        feature_act=arch.feature_act,
        classifier=classifier,
        dropout=dropout,
        image_size=image_size,
    )
