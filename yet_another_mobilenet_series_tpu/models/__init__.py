"""Model zoo + constructor (reference: models/get_model, SURVEY.md §2 #4)."""

from __future__ import annotations

import dataclasses

from ..config import ModelConfig
from .specs import ArchDef, Network, build_network
from .zoo import ARCHS, get_arch

__all__ = ["ArchDef", "Network", "build_network", "get_arch", "get_model", "ARCHS"]


def get_model(cfg: ModelConfig, image_size: int = 224) -> Network:
    """Resolve a ModelConfig into a concrete Network spec."""
    if cfg.network_spec:
        # a serialized Network (e.g. searched_arch.json emitted by an AtomNAS
        # run) IS the architecture; classifier width must match num_classes
        import dataclasses as _dc
        import json

        from .serialize import network_from_dict

        with open(cfg.network_spec) as f:
            payload = json.load(f)
        net = network_from_dict(payload.get("network", payload))
        if net.classifier.out_features != cfg.num_classes:
            raise ValueError(
                f"network_spec has {net.classifier.out_features} classes, config wants {cfg.num_classes}"
            )
        if cfg.drop_connect is not None:
            if not 0.0 <= cfg.drop_connect < 1.0:
                raise ValueError(f"drop_connect must be in [0, 1), got {cfg.drop_connect}")
            # like dropout, drop_connect is a training knob, not part of the
            # serialized architecture: re-apply the linear depth ramp
            # (models/specs.py) over the restored blocks
            nb = len(net.blocks)
            net = _dc.replace(net, blocks=tuple(
                _dc.replace(b, drop_path=cfg.drop_connect * i / nb) for i, b in enumerate(net.blocks)
            ))
        return _dc.replace(net, dropout=cfg.dropout, image_size=image_size)
    arch = get_arch(cfg.arch)
    if cfg.active_fn is not None:
        arch = dataclasses.replace(
            arch, stem_act=cfg.active_fn, head_act=cfg.active_fn, default_act=cfg.active_fn
        )
    # explicit channel overrides are EXACT final widths, exempt from
    # width_mult scaling (build_network docstring)
    exact = {}
    if cfg.stem_channels is not None:
        exact["stem"] = cfg.stem_channels
    if cfg.head_channels is not None:
        exact["head"] = cfg.head_channels
    if cfg.feature_channels is not None:
        exact["feature"] = cfg.feature_channels
    return build_network(
        arch,
        width_mult=cfg.width_mult,
        num_classes=cfg.num_classes,
        dropout=cfg.dropout,
        bn_momentum=cfg.bn_momentum,
        bn_eps=cfg.bn_eps,
        image_size=image_size,
        block_specs_override=cfg.block_specs,
        exact_channels=exact or None,
        drop_connect=cfg.drop_connect,
    )
