"""Fleet router: weighted routing over N replica frontends.

One replica process is bounded by one host; the millions-of-users story
needs a shared-nothing fleet behind one address. The router is that
address. It speaks the SAME protocol the admission controller speaks
(``submit(image, priority, deadline_ms, ctx) -> Future`` + ``state()``), so
``serve/frontend.py`` can serve it directly — the fleet exposes the exact
endpoints, typed statuses, and ``X-Request-Id`` threading one replica does,
and a client cannot tell N replicas from one.

Routing policy, all driven by what the replicas THEMSELVES report:

- **health polling**: a daemon thread polls every backend's ``/healthz`` at
  ``poll_interval_s``. Each poll refreshes the replica's queue depth
  (``queued_total``), breaker state, draining flag, and identity block
  (``replica_id``/``pid``/``start_unix`` — a changed ``start_unix`` behind
  the same address is a detected restart, ``fleet.replica_restarts``).
- **weighted pick**: routable replicas are drawn with weight
  ``1 / (1 + queue_depth)`` (seeded RNG — reproducible in tests), so load
  skews away from backed-up replicas without starving anyone.
- **ejection / readmission**: ``eject_failures`` consecutive failures
  (poll or dispatch transport errors), an open breaker, or a draining flag
  eject a replica from rotation (``fleet.ejections``); the next healthy
  poll readmits it (``fleet.readmissions``). Ejection is advisory — with
  every replica ejected the router fails typed
  (:class:`NoHealthyReplicas` -> 503), never silently.
- **latency-based soft ejection** (gray failure): crash counters never fire
  for a slow-but-alive replica, so one straggler poisons the fleet p99
  forever. Each replica carries an EWMA of its per-leg dispatch latency;
  every poll sweep compares it against the fleet's (lower) median. A
  multiplicative outlier (``slow_factor`` x median, above an absolute
  ``slow_min_ms`` floor) first has its routing weight DECAYED (halved per
  outlier sweep — load skews away before anything is ejected), and after
  ``slow_eject_after`` consecutive outlier sweeps is ejected
  (``fleet.slow_ejections``, also counted in ``fleet.ejections``). It
  readmits through the existing healthy-poll path after a
  ``slow_cooldown_s`` probation, with a fresh latency estimate — still
  slow, it walks the same decay-then-eject path again; recovered, it stays.
- **backpressure vs death**: a 503 carrying ``Retry-After`` is an
  overloaded-but-healthy replica (breaker cooldown, brownout shed) — the
  request re-routes (``fleet.backpressure``) but the replica's ejection
  counter is NOT touched; a 503 without it (draining, nothing routable
  behind a nested router) scores toward ejection like a transport failure.
- **poll desynchronization**: each replica's next health poll is scheduled
  with per-replica seeded jitter around ``poll_interval_s``, so N routers
  x M replicas cannot phase-lock into a thundering poll herd.
- **transport retry**: a dead socket (:class:`~.client.ClientConnectError`),
  a transport-level read timeout (:class:`~.client.ClientTimeout` — a
  half-open socket or a response-eating link; inference is pure, so the
  duplicate risk is only wasted work), or a replica-side 503 (draining /
  its own breaker) re-routes the request to the next replica
  (``fleet.route_retries``); typed per-request verdicts (429 quota, 504
  deadline, 500 engine error) pass through unchanged — the replica already
  ran ITS retry policy.
- **partition awareness**: the client splits its CONNECT timeout from its
  read timeout (``connect_timeout_s``), and the health poll's read bound
  derives from the connect budget — a /healthz answers in microseconds, so
  a poll that cannot finish inside the connect budget is a partition, not
  a slow reply. A blackholed replica therefore ejects within
  ``eject_failures`` poll sweeps x (interval + connect timeout), never the
  60 s read budget. Ejections whose terminal failure was transport-shaped
  (connect failure / timeout) count ``fleet.partition_ejections``, and an
  ejected replica serves an ``eject_cooldown_s`` probation before a
  healthy poll may readmit it — a flapping link produces ONE bounded
  eject/readmit cycle per cooldown instead of ping-ponging every flap.
- **TTL-leased membership** (the multi-host rung): besides the
  statically-configured backend set (:meth:`set_backends` — the local
  supervisor's view), replicas REGISTER themselves (:meth:`register`, via
  POST /register on the router's frontend) with a TTL lease renewed by
  heartbeat (``fleet.registrations`` / ``fleet.lease_renewals``). A lease
  that expires unrenewed REMOVES the backend (``fleet.lease_expirations``)
  — a silently-vanished host leaves the fleet without anyone having to
  notice it, which no crash signal can do across machines.
- **hedging** (serve/hedge.py): when a :class:`~.hedge.Hedger` is attached
  and >= 2 replicas are routable, a timer fires at the class's p99-derived
  bound and sends a duplicate to a second replica (primary's replica
  excluded); first answer wins, the loser is dropped idempotently.

Instrumentation: ``fleet.routed`` / ``fleet.route_retries`` /
``fleet.route_errors`` / ``fleet.ejections`` / ``fleet.readmissions`` /
``fleet.replica_restarts`` counters, the ``fleet.replicas_routable`` gauge,
per-class ``serve.router.latency_seconds.<class>`` histograms (the hedge
timer's input), and a ``fleet/route`` span per request.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..utils.logging import emit
from .admission import CLASSES, BrownoutShed
from .client import (
    ClientConnectError,
    ClientError,
    ClientHTTPError,
    ClientTimeout,
    ReplicaClient,
)
from .context import TRACE_SEQ_HEDGE_BASE, trace_flow_id
from .hedge import ROUTER_LATENCY, HedgedCall, Hedger


class NoHealthyReplicas(RuntimeError):
    """Every replica is ejected or the backend set is empty: the fleet
    cannot serve this request (mapped to 503 by the frontend)."""


class NoReplicaForModel(NoHealthyReplicas):
    """Replicas are routable, but none ADVERTISES the request's model: a
    placement gap, not a health failure. Subclasses NoHealthyReplicas so
    every existing 503 mapping holds; carries the model so the frontend
    can tag the verdict distinctly."""

    def __init__(self, model: str, served: tuple = ()):  # noqa: D107
        self.model = model
        self.served = tuple(sorted(served))
        super().__init__(
            f"no routable replica serves model {model!r}"
            + (f"; fleet serves: {', '.join(self.served)}" if self.served else "")
        )


class ModelDigestConflict(ValueError):
    """Two replicas are advertising the SAME model name with DIFFERENT
    content digests: routing would be a lottery over which weights answer.
    The conflicting registration is refused (mapped to 409 by the
    frontend); the operator must converge the fleet on one artifact."""


class _Replica:
    """Router-side view of one backend: client + polled health."""

    __slots__ = ("key", "host", "port", "client", "routable", "consecutive_failures",
                 "queue_depth", "breaker_state", "draining", "identity",
                 "lat_ewma_s", "slow_strikes", "slow_until", "weight_scale", "next_poll_t",
                 "source", "lease_until", "eject_until", "models")

    def __init__(self, host: str, port: int, client, source: str = "static"):
        self.key = f"{host}:{port}"
        self.host = host
        self.port = port
        self.client = client
        self.routable = True
        self.consecutive_failures = 0
        self.queue_depth = 0.0
        self.breaker_state = 0
        self.draining = False
        self.identity: dict = {}
        # gray-failure bookkeeping: EWMA of per-LEG dispatch latency (None
        # until the first success), consecutive outlier-sweep strikes, the
        # probation deadline a slow ejection imposes, and the multiplicative
        # weight decay applied while this replica is an outlier
        self.lat_ewma_s: float | None = None
        self.slow_strikes = 0
        self.slow_until = 0.0
        self.weight_scale = 1.0
        # per-replica jittered poll schedule (monotonic deadline)
        self.next_poll_t = 0.0
        # membership: "static" (set_backends — the supervisor's view, no
        # lease) or "lease" (self-registered with a TTL, expires unrenewed)
        self.source = source
        self.lease_until: float | None = None
        # post-ejection probation (monotonic): a healthy poll may not
        # readmit before this — the flap-ping-pong damper
        self.eject_until = 0.0
        # model-sharded placement: {model_name: digest} the replica's lease
        # advertised ('' = unstamped pre-zoo bundle). None = no advertisement
        # (static member / pre-zoo replica) — routable for EVERY model, so a
        # zoo-unaware fleet keeps the pre-zoo routing behavior
        self.models: dict[str, str] | None = None

    def weight(self) -> float:
        return self.weight_scale / (1.0 + max(self.queue_depth, 0.0))

    def as_dict(self) -> dict:
        return {
            "key": self.key,
            "source": self.source,
            "routable": self.routable,
            "queue_depth": self.queue_depth,
            "breaker_state": self.breaker_state,
            "draining": self.draining,
            "consecutive_failures": self.consecutive_failures,
            "lat_ewma_ms": round(self.lat_ewma_s * 1e3, 3) if self.lat_ewma_s is not None else None,
            "slow_strikes": self.slow_strikes,
            "weight_scale": self.weight_scale,
            "identity": self.identity,
            "models": sorted(self.models) if self.models is not None else None,
        }


class Router:
    """Weighted fleet router implementing the frontend's admission protocol."""

    def __init__(
        self,
        backends=(),
        *,
        default_class: str = "interactive",
        poll_interval_s: float = 0.25,
        eject_failures: int = 2,
        route_attempts: int = 3,
        client_timeout_s: float = 60.0,
        hedger: Hedger | None = None,
        seed: int = 0,
        max_workers: int = 32,
        client_factory=None,
        poll_jitter: float = 0.2,
        slow_eject: bool = False,
        slow_factor: float = 3.0,
        slow_eject_after: int = 3,
        slow_cooldown_s: float = 5.0,
        slow_min_ms: float = 1.0,
        lat_alpha: float = 0.3,
        connect_timeout_s: float | None = None,
        eject_cooldown_s: float = 0.0,
        lease_ttl_s: float = 5.0,
    ):
        if default_class not in CLASSES:
            raise ValueError(f"default_class {default_class!r} not in {CLASSES}")
        if not 0.0 <= poll_jitter < 1.0:
            raise ValueError(f"poll_jitter must be in [0, 1), got {poll_jitter}")
        if slow_factor <= 1.0:
            raise ValueError(f"slow_factor must be > 1 (a multiplicative outlier), got {slow_factor}")
        self._default_class = default_class
        self._poll_interval_s = poll_interval_s
        self._poll_jitter = poll_jitter
        self._eject_failures = max(1, int(eject_failures))
        self._route_attempts = max(1, int(route_attempts))
        self._client_timeout_s = client_timeout_s
        self._hedger = hedger
        self._hedging_enabled = True  # brownout L1+ flips this off
        self._shed_classes: frozenset[str] = frozenset()
        self._brownout_level = 0
        self._brownout_retry_after_s = 1.0
        self._slow_eject = bool(slow_eject)
        self._slow_factor = float(slow_factor)
        self._slow_eject_after = max(1, int(slow_eject_after))
        self._slow_cooldown_s = float(slow_cooldown_s)
        self._slow_min_s = slow_min_ms / 1e3
        self._lat_alpha = float(lat_alpha)
        # None = the pre-split single-timeout client (r06 semantics); set,
        # it bounds the TCP handshake AND the health poll's read budget — a
        # /healthz that cannot answer inside the connect budget is a
        # partition, not a slow reply
        self._connect_timeout_s = connect_timeout_s
        self._eject_cooldown_s = float(eject_cooldown_s)
        self._lease_ttl_s = float(lease_ttl_s)
        self._rng = random.Random(seed)
        # the poll scheduler's own stream: pick draws must not perturb the
        # deterministic per-replica jitter (and vice versa)
        self._poll_rng = random.Random(seed + 0x9E37)
        self._client_factory = client_factory or (
            lambda host, port: ReplicaClient(
                host, port, timeout_s=client_timeout_s,
                connect_timeout_s=connect_timeout_s,
            )
        )
        self._lock = threading.Lock()
        self._replicas: dict[str, _Replica] = {}
        self._pool = ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="fleet-route")
        self._poll_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._reg = get_registry()
        # flight-recorder hook: called with (kind, **fields) for significant
        # fleet events. Some emit sites hold self._lock, so the sink MUST be
        # non-blocking (obs/fleet.py FlightRecorder.record is a deque append)
        self._event_sink = None
        # in-flight ledger: token -> submit record, for the watchdog's
        # "oldest in-flight request" hang-report provider. Tokens are
        # monotonic, so min(token) is the oldest submit
        self._inflight: dict[int, dict] = {}
        self._inflight_ids = itertools.count(1)
        self.set_backends(backends)

    # -- flight-recorder event sink ------------------------------------------

    def set_event_sink(self, sink) -> None:
        """Attach a ``fn(kind, **fields)`` receiving significant fleet
        events (ejections, readmissions, lease expirations, breaker flips,
        hedge outcomes, terminal failures, sheds). The sink is called from
        routing/poll threads — sometimes UNDER the router lock — so it must
        be non-blocking and must not call back into the router."""
        self._event_sink = sink  # yamt-lint: disable=YAMT019 — single-writer wiring at startup; emit sites read the slot lock-free by design

    def _emit_event(self, kind: str, **fields) -> None:
        sink = self._event_sink
        if sink is None:
            return
        try:
            sink(kind, **fields)
        except Exception:  # noqa: BLE001 — observability must never fail routing
            self._reg.counter("fleet.event_sink_errors").inc()

    # -- backend set (the supervisor / autoscaler mutate this) ---------------

    def set_backends(self, backends) -> None:
        """Reconcile the STATIC replica set against ``backends`` (iterable
        of ``(host, port)`` or ``"host:port"``). New backends start
        routable; removed backends have their clients closed. Leased
        (self-registered) members are NOT touched — a local supervisor's
        membership notifications must never evict a remote host that is
        faithfully renewing its lease."""
        want: dict[str, tuple[str, int]] = {}
        for b in backends:
            host, port = b.rsplit(":", 1) if isinstance(b, str) else b
            want[f"{host}:{int(port)}"] = (host, int(port))
        with self._lock:
            for key in [k for k in self._replicas
                        if k not in want and self._replicas[k].source == "static"]:
                rep = self._replicas.pop(key)
                rep.client.close()
            for key, (host, port) in want.items():
                if key not in self._replicas:
                    self._replicas[key] = _Replica(host, port, self._client_factory(host, port))
                elif self._replicas[key].source == "lease":
                    # the supervisor now owns an address that self-registered
                    # earlier: promote it — static membership outranks leases
                    self._replicas[key].source = "static"
                    self._replicas[key].lease_until = None
            self._update_routable_gauge_locked()

    def set_backend_models(self, assignments: dict) -> None:
        """Attach served-model advertisements to members by key
        (``"host:port" -> {model: digest}`` — digest '' when the caller
        only knows placement, e.g. the local supervisor's slot assignment).
        Unknown keys are skipped (the member may have just died); a key
        mapped to None clears its advertisement (routes everything)."""
        with self._lock:
            for key, models in assignments.items():
                rep = self._replicas.get(key)
                if rep is None:
                    continue
                rep.models = (
                    None if models is None
                    else {str(n): str(d or "") for n, d in dict(models).items()}
                )

    # -- TTL-leased membership (the multi-host registration path) ------------

    def register(self, host: str, port: int, *, ttl_s: float | None = None,
                 replica_id: str = "", models=None) -> dict:
        """Admit (or heartbeat-renew) a self-registered backend with a TTL
        lease. First sight counts ``fleet.registrations``; renewals count
        ``fleet.lease_renewals``; a lease that expires unrenewed is swept
        out of membership by the poll loop (``fleet.lease_expirations``).
        Registering an address the static set already owns is a harmless
        renewal no-op (static membership has no lease to expire).

        ``models`` is the replica's served-model advertisement,
        ``{name: digest}`` (digest '' for an unstamped bundle) — the
        model-aware pick routes a request for model M only to replicas
        advertising M. A registration advertising a name whose NON-EMPTY
        digest differs from another live replica's for the same name is
        refused (:class:`ModelDigestConflict`,
        ``fleet.rejected_digest_conflict``): a split-brain fleet where one
        name maps to two different artifacts must fail the late joiner
        loudly, not answer from whichever replica the weighted pick lands
        on."""
        ttl = float(ttl_s) if ttl_s else self._lease_ttl_s
        if ttl <= 0:
            raise ValueError(f"lease ttl_s must be > 0, got {ttl}")
        adv: dict[str, str] | None = None
        if models is not None:
            adv = {str(name): str(digest or "") for name, digest in dict(models).items()}
        key = f"{host}:{int(port)}"
        now = time.monotonic()
        with self._lock:
            if adv:
                for other in self._replicas.values():
                    if other.key == key or not other.models:
                        continue
                    for name, digest in adv.items():
                        have = other.models.get(name)
                        if digest and have and have != digest:
                            self._reg.counter("fleet.rejected_digest_conflict").inc()
                            self._emit_event("digest_conflict", replica=key,
                                             model=name, digest=digest,
                                             holder=other.key, holder_digest=have)
                            raise ModelDigestConflict(
                                f"replica {key} advertises model {name!r} with digest "
                                f"{digest} but live replica {other.key} serves digest "
                                f"{have}; refusing registration — one name, one artifact"
                            )
            rep = self._replicas.get(key)
            if rep is None:
                rep = _Replica(host, int(port), self._client_factory(host, int(port)),
                               source="lease")
                rep.lease_until = now + ttl
                rep.models = adv
                self._replicas[key] = rep
                self._reg.counter("fleet.registrations").inc()
                self._update_routable_gauge_locked()
                new = True
            else:
                if rep.source == "lease":
                    rep.lease_until = now + ttl
                if adv is not None:
                    rep.models = adv
                self._reg.counter("fleet.lease_renewals").inc()
                new = False
        return {"ok": True, "key": key, "ttl_s": ttl, "new": new,
                "source": rep.source, "replica_id": replica_id,
                "models": sorted(adv) if adv is not None else None}

    def deregister(self, host: str, port: int) -> dict:
        """Drop a leased membership immediately (the clean-drain path —
        faster than waiting out the TTL). Static members are supervisor-
        owned and stay; unknown keys are a no-op."""
        key = f"{host}:{int(port)}"
        with self._lock:
            rep = self._replicas.get(key)
            if rep is None or rep.source != "lease":
                return {"ok": False, "key": key,
                        "reason": "unknown" if rep is None else "static"}
            self._replicas.pop(key)
            rep.client.close()
            self._update_routable_gauge_locked()
        self._reg.counter("fleet.deregistrations").inc()
        return {"ok": True, "key": key}

    def _sweep_leases_locked(self, now: float) -> None:
        """Remove leased members whose TTL ran out unrenewed: the replica
        (or its host, or the path to it) is gone — membership must not keep
        routing weight parked on a ghost."""
        expired = [k for k, r in self._replicas.items()
                   if r.source == "lease" and r.lease_until is not None
                   and now >= r.lease_until]
        for key in expired:
            rep = self._replicas.pop(key)
            rep.client.close()
            self._reg.counter("fleet.lease_expirations").inc()
            self._emit_event("lease_expired", replica=key)
        if expired:
            self._update_routable_gauge_locked()

    def _update_routable_gauge_locked(self) -> None:
        self._reg.gauge("fleet.replicas_routable").set(
            sum(1 for r in self._replicas.values() if r.routable)
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Router":
        if self._poll_thread is not None:
            raise RuntimeError("router already started")
        self._stop.clear()
        self._poll_thread = threading.Thread(target=self._poll_loop, name="fleet-poll", daemon=True)
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
            self._poll_thread = None
        self._pool.shutdown(wait=False, cancel_futures=True)
        with self._lock:
            for rep in self._replicas.values():
                rep.client.close()

    # -- health polling ------------------------------------------------------

    def _poll_loop(self) -> None:
        try:  # YAMT011: a silently-dead poll thread would freeze health state
            obs_trace.get_tracer().register_thread()
            # the loop ticks FASTER than the poll interval and polls only the
            # replicas whose jittered deadline has passed — per-replica
            # schedules drift apart instead of firing as one herd
            tick = max(self._poll_interval_s / 4.0, 0.02)
            while not self._stop.wait(tick):
                self.poll_once(now=time.monotonic())
        except Exception as e:  # noqa: BLE001 — contain, count, report
            self._reg.counter("serve.thread_crashes").inc()
            emit(f"[fleet] router poll thread crashed: {type(e).__name__}: {e}")

    def _next_poll_t(self, now: float) -> float:
        """The next jittered poll deadline: interval scaled by a seeded draw
        in [1 - jitter, 1 + jitter], per replica per poll — N routers x M
        replicas starting together desynchronize within a few intervals
        instead of thundering every /healthz at once."""
        factor = 1.0 + self._poll_jitter * self._poll_rng.uniform(-1.0, 1.0)
        return now + self._poll_interval_s * factor

    def poll_once(self, now: float | None = None) -> None:
        """One health sweep. With ``now`` (the poll thread's monotonic
        clock), only replicas whose jittered deadline has passed are polled;
        called bare (tests, the bench's deterministic refreshes) it polls
        every backend immediately."""
        force = now is None
        now = time.monotonic() if now is None else now
        with self._lock:
            self._sweep_leases_locked(now)
            reps = [r for r in self._replicas.values() if force or now >= r.next_poll_t]
        # the poll's read budget: /healthz answers in microseconds, so a
        # poll is bounded by the CONNECT budget when one is configured — a
        # blackholed replica then ejects in ~eject_failures x (interval +
        # connect timeout), never the 60 s read timeout
        if self._connect_timeout_s is not None:
            poll_timeout = max(self._connect_timeout_s, 2 * self._poll_interval_s)
        else:
            poll_timeout = max(2.0, 4 * self._poll_interval_s)
        for rep in reps:
            rep.next_poll_t = self._next_poll_t(now)
            try:
                status, doc = rep.client.healthz(timeout_s=poll_timeout)
            except ClientError as e:
                # a poll that TIMES OUT is partition-shaped (blackhole /
                # half-open); a refused/reset one is crash-shaped — both
                # score the same counter, but the ejection they cause is
                # attributed differently (fleet.partition_ejections)
                self._record_failure(
                    rep, kind="timeout" if isinstance(e, ClientTimeout) else "connect",
                    now=now,
                )
                continue
            identity = doc.get("replica") or {}
            with self._lock:
                rep.consecutive_failures = 0
                rep.queue_depth = float(doc.get("queued_total") or 0.0)
                breaker = int(doc.get("breaker_state") or 0)
                if breaker != rep.breaker_state:
                    self._emit_event("breaker_flip", replica=rep.key,
                                     state=breaker, prev=rep.breaker_state)
                rep.breaker_state = breaker
                rep.draining = bool(doc.get("draining"))
                if (identity and rep.identity
                        and identity.get("start_unix") != rep.identity.get("start_unix")):
                    # same address, new process: a supervisor restarted it
                    self._reg.counter("fleet.replica_restarts").inc()
                if identity:
                    rep.identity = identity
                # a slow- or crash-ejected replica serves out its probation
                # before a healthy poll may readmit it (otherwise the very
                # next sweep would readmit and a flapping link would
                # ping-pong eject/readmit every cycle)
                healthy = (status == 200 and not rep.draining
                           and now >= rep.slow_until and now >= rep.eject_until)
                self._set_routable_locked(rep, healthy)
        if reps:
            self._slow_sweep(now)

    # -- gray-failure detection (latency-based soft ejection) ----------------

    def _slow_sweep(self, now: float) -> None:
        """Compare every routable replica's per-leg latency EWMA against the
        fleet's LOWER median (robust in 2-replica fleets: the outlier never
        drags its own threshold up). A multiplicative outlier decays its
        routing weight first; ``slow_eject_after`` consecutive outlier
        sweeps eject it (``fleet.slow_ejections``) into a
        ``slow_cooldown_s`` probation, after which the ordinary healthy
        poll readmits it with a fresh estimate."""
        if not self._slow_eject:
            return
        with self._lock:
            scored = [r for r in self._replicas.values()
                      if r.routable and r.lat_ewma_s is not None]
            if len(scored) < 2:
                return  # no fleet to be an outlier OF
            med = sorted(r.lat_ewma_s for r in scored)[(len(scored) - 1) // 2]
            threshold = max(med * self._slow_factor, self._slow_min_s)
            for rep in scored:
                if rep.lat_ewma_s > threshold:
                    rep.slow_strikes += 1
                    # decay first: load skews away before anything ejects
                    rep.weight_scale = max(rep.weight_scale * 0.5, 1.0 / 16.0)
                    if rep.slow_strikes >= self._slow_eject_after:
                        self._reg.counter("fleet.slow_ejections").inc()
                        self._set_routable_locked(rep, False)
                        rep.slow_until = now + self._slow_cooldown_s
                        # probation starts clean: the estimate that ejected
                        # it must not re-eject it before it serves a request
                        rep.slow_strikes = 0
                        rep.weight_scale = 1.0
                        rep.lat_ewma_s = None
                else:
                    rep.slow_strikes = 0
                    rep.weight_scale = min(1.0, rep.weight_scale * 2.0)

    def _set_routable_locked(self, rep: _Replica, routable: bool) -> None:
        if routable and not rep.routable:
            rep.routable = True
            self._reg.counter("fleet.readmissions").inc()
            self._emit_event("readmission", replica=rep.key)
        elif not routable and rep.routable:
            rep.routable = False
            self._reg.counter("fleet.ejections").inc()
            self._emit_event("ejection", replica=rep.key,
                             consecutive_failures=rep.consecutive_failures)
        self._update_routable_gauge_locked()

    def _record_failure(self, rep: _Replica, kind: str = "connect",
                        now: float | None = None) -> None:
        """Score one transport-shaped failure against a replica. ``kind`` is
        "connect" (refused/reset/dead socket), "timeout" (blackhole /
        half-open — the partition shapes), or "http" (a 503 with no
        comeback hint). The ejection it triggers starts the
        ``eject_cooldown_s`` probation, and transport-shaped kinds count
        ``fleet.partition_ejections`` so a fleet operator can tell a
        network event from a crash loop in one counter."""
        now = time.monotonic() if now is None else now
        with self._lock:
            rep.consecutive_failures += 1
            if rep.consecutive_failures >= self._eject_failures:
                if rep.routable and kind in ("connect", "timeout"):
                    self._reg.counter("fleet.partition_ejections").inc()
                self._set_routable_locked(rep, False)
                rep.eject_until = now + self._eject_cooldown_s

    # -- picking -------------------------------------------------------------

    def _pick(self, exclude: set[str], model: str | None = None) -> _Replica:
        with self._lock:
            pool = [r for r in self._replicas.values() if r.routable and r.key not in exclude]
            if not pool:
                raise NoHealthyReplicas(
                    f"no routable replica ({len(self._replicas)} registered, "
                    f"{len(exclude)} excluded)"
                )
            if model is not None:
                # model-sharded placement: only replicas ADVERTISING the
                # model may answer for it (None advertisement = pre-zoo
                # replica, serves everything). Healthy-but-wrong-model is a
                # placement gap, distinct from NoHealthyReplicas
                served = [r for r in pool if r.models is None or model in r.models]
                if not served:
                    raise NoReplicaForModel(
                        model,
                        {m for r in pool if r.models for m in r.models},
                    )
                pool = served
            weights = [r.weight() for r in pool]
            return self._rng.choices(pool, weights=weights, k=1)[0]

    def set_hedger(self, hedger: Hedger | None) -> None:
        """Swap the hedging policy live (the serve_bench A/B drives both
        arms through ONE router so replica state is shared)."""
        self._hedger = hedger

    def set_slow_ejection(self, enabled: bool) -> None:
        """Flip gray-failure soft ejection live (the --overload bench warms
        the fleet with it off, then arms it at the round start so
        time-to-eject is measured from a known instant)."""
        self._slow_eject = bool(enabled)  # yamt-lint: disable=YAMT019 — bench actuator: single-writer bool flip; the poll loop reads it lock-free by design

    def apply_brownout(self, policy) -> None:
        """The router's slice of a :class:`~.brownout.BrownoutPolicy`:
        hedging on/off (L1 stops duplicating work first) and the classes
        the fleet door sheds with Retry-After (L3+)."""
        self._hedging_enabled = bool(policy.hedging)
        self._shed_classes = frozenset(policy.shed_classes)
        self._brownout_level = int(policy.level)
        self._brownout_retry_after_s = float(policy.retry_after_s)

    def n_routable(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values() if r.routable)

    def mean_queue_depth(self) -> float:
        """Mean polled queue depth across routable replicas (the
        autoscaler's backlog signal); 0 with nothing routable."""
        with self._lock:
            depths = [r.queue_depth for r in self._replicas.values() if r.routable]
        return sum(depths) / len(depths) if depths else 0.0

    # -- the serving protocol (what Frontend consumes) -----------------------

    def submit(self, image, *, priority: str | None = None,
               deadline_ms: float | None = None, ctx=None,
               model: str | None = None, seq_base: int | None = None) -> Future:
        # the request's model: explicit kwarg wins, else the ctx's parsed
        # X-Model, else None (pre-zoo request — any replica may answer).
        # seq_base overrides the primary leg's trace-seq origin (the
        # cascade's escalation legs stamp TRACE_SEQ_CASCADE_BASE so a merged
        # trace tells an escalation from a first-tier attempt)
        model = model or (ctx.model if ctx is not None else None)
        cls = priority or self._default_class
        if cls not in CLASSES:
            raise ValueError(f"unknown priority class {cls!r}; valid: {CLASSES}")
        if cls in self._shed_classes:
            # brownout at the FLEET door: cheaper than a hop to any replica
            self._reg.counter("serve.rejected_brownout").inc()
            self._emit_event("request_shed", cls=cls, level=self._brownout_level,
                             rid=ctx.wire_id if ctx is not None else None)
            raise BrownoutShed(
                f"class {cls!r} shed at brownout level L{self._brownout_level}; "
                f"retry after {self._brownout_retry_after_s:.1f}s",
                retry_after_s=self._brownout_retry_after_s,
            )
        fut: Future = Future()
        call = HedgedCall(fut)
        # preserve a uint8 wire body (X-Dtype: u8) end-to-end: forcing f32
        # here would silently 4x the router->replica bytes the quantized
        # wire exists to save; anything else stays on the f32 contract
        image = np.asarray(image)
        if image.dtype != np.uint8:
            image = np.asarray(image, np.float32)
        # latency is measured from HERE (submit), not from leg start: router
        # queueing is part of what a client experiences, so the histogram
        # the autoscaler and hedge timer read must include it
        t_submit = time.perf_counter()
        token = next(self._inflight_ids)
        with self._lock:
            self._inflight[token] = {
                "t0": t_submit, "cls": cls,
                "rid": ctx.rid if ctx is not None else None,
            }
        if ctx is not None:
            # router-side request envelope: the router process gets its own
            # serve/request async span keyed by the ROUTER rid (= the fleet
            # trace id the legs carry), so a merged trace shows the fleet
            # view of the request above the per-leg and replica rows
            ctx.open_envelope()
            ctx.advance("queued")

        def _settle(f: Future, token: int = token, ctx=ctx) -> None:
            with self._lock:
                self._inflight.pop(token, None)
            if ctx is None:
                return
            try:
                failed = f.exception() is not None
            except Exception:  # noqa: BLE001 — a cancelled future is "failed"
                failed = True
            ctx.advance("failed" if failed else "completed")
            ctx.close_envelope()

        fut.add_done_callback(_settle)
        self._pool.submit(self._route_guarded, call, image, cls, deadline_ms, ctx,
                          t_submit, model, seq_base)
        return fut

    def _route_guarded(self, call, image, cls, deadline_ms, ctx, t_submit,
                       model=None, seq_base=None) -> None:
        trace_id = ctx.rid if ctx is not None else None
        try:
            self._route(call, image, cls, deadline_ms, ctx, t_submit, model, seq_base)
        except Exception as e:  # noqa: BLE001 — a crashed route must not hang its client
            self._reg.counter("fleet.route_errors").inc()
            self._fail_leg(call, HedgedCall.PRIMARY, e, cls=cls, trace_id=trace_id)

    def _route(self, call, image, cls, deadline_ms, ctx, t_submit,
               model=None, seq_base=None) -> None:
        rid = ctx.wire_id if ctx is not None else None
        # the fleet trace id every leg's X-Trace-Parent carries: the
        # router's own monotonic rid (context.py parse_trace_parent)
        trace_id = ctx.rid if ctx is not None else None
        timer: threading.Timer | None = None
        primary_at: dict = {}
        hedge_s = None
        if self._hedger is not None:
            if self._hedging_enabled:
                hedge_s = self._hedger.timer_s(cls)
            else:
                # brownout L1+: a timer that WOULD have armed is counted as
                # suppressed — the "work we chose not to duplicate" instrument
                if self._hedger.timer_s(cls) is not None and self.n_routable() >= 2:
                    self._hedger.suppressed()
        # the hedge timer arms at LEG start, while the histogram it derives
        # from measures submit -> resolution: under router-side overload the
        # timer inflates past per-leg latency, so hedging naturally backs
        # off instead of doubling the load of an already-saturated fleet
        if hedge_s is not None and self.n_routable() >= 2:
            timer = threading.Timer(
                hedge_s, self._fire_hedge,
                args=(call, image, cls, deadline_ms, rid, trace_id, primary_at, t_submit,
                      model),
            )
            timer.daemon = True
            timer.start()
        try:
            targs = {"trace": trace_id} if trace_id is not None else {}
            if model is not None:
                targs["model"] = model
            with obs_trace.get_tracer().span("fleet/route", "serve", cls=cls, **targs):
                self._leg(call, HedgedCall.PRIMARY, image, cls, deadline_ms, rid,
                          exclude=set(), chosen=primary_at, t_submit=t_submit,
                          trace_id=trace_id, model=model, seq_base=seq_base)
        finally:
            if timer is not None and call.resolved:
                timer.cancel()

    def _fire_hedge(self, call, image, cls, deadline_ms, rid, trace_id, primary_at,
                    t_submit, model=None) -> None:
        try:  # Timer threads die as silently as any other (YAMT011 discipline)
            if not call.launch_hedge():
                return  # primary already resolved; nothing to duplicate
            exclude = {primary_at["key"]} if "key" in primary_at else set()
            self._leg(call, HedgedCall.HEDGE, image, cls, deadline_ms, rid,
                      exclude=exclude, t_submit=t_submit, trace_id=trace_id,
                      model=model)
        except Exception as e:  # noqa: BLE001 — contain: fail the leg, not the thread
            self._reg.counter("fleet.route_errors").inc()
            self._fail_leg(call, HedgedCall.HEDGE, e, cls=cls, trace_id=trace_id)

    def _fail_leg(self, call, leg, exc, *, cls, trace_id) -> None:
        """Deliver a leg failure; when THIS call settles the request (no
        other leg can still answer), record the terminal verdict for the
        flight recorder — failed requests leave a per-request record."""
        if call.err(leg, exc):
            self._emit_event("request_failed", trace=trace_id, cls=cls, leg=leg,
                             error=type(exc).__name__)

    def _leg(self, call, leg, image, cls, deadline_ms, rid, *, exclude, chosen=None,
             t_submit=None, trace_id=None, model=None, seq_base=None) -> None:
        """One leg (primary or hedge) of one request: pick, dispatch, retry
        transport-level failures on other replicas, resolve the call.

        Trace propagation: each ATTEMPT of each leg gets a distinct seq
        (hedge attempts offset by TRACE_SEQ_HEDGE_BASE; a cascade
        escalation's primary legs by TRACE_SEQ_CASCADE_BASE via
        ``seq_base``) stamped into the ``X-Trace-Parent`` header, plus a
        ``fleet/leg`` span with a flow arrow whose id the replica's
        ``link_parent`` flow-end shares — the merged trace draws
        router -> leg -> replica per attempt."""
        tracer = obs_trace.get_tracer()
        tried = set(exclude)
        last_exc: Exception | None = None
        if leg == HedgedCall.HEDGE:
            seq_base = TRACE_SEQ_HEDGE_BASE
        elif seq_base is None:
            seq_base = 0
        for attempt in range(self._route_attempts):
            try:
                rep = self._pick(tried, model)
            except NoHealthyReplicas as e:
                self._fail_leg(call, leg, last_exc or e, cls=cls, trace_id=trace_id)
                return
            if chosen is not None:
                chosen["key"] = rep.key
            tp = None
            targs = {}
            if trace_id is not None:
                # seq < 16 is the parse_trace_parent contract; retries must
                # stay inside their band (primary 0..3, cascade 4..7, hedge
                # 8..15), so clamp to the band width (route_attempts is
                # small — <= ~3 — in any real config)
                span = ((TRACE_SEQ_HEDGE_BASE - seq_base)
                        if seq_base < TRACE_SEQ_HEDGE_BASE else (16 - seq_base))
                seq = seq_base + min(attempt, span - 1)
                tp = f"{trace_id}-{seq}-{leg}"
                targs = {"trace": trace_id, "leg": leg, "seq": seq}
                if model is not None:
                    targs["model"] = model
            t0 = time.perf_counter() if t_submit is None else t_submit
            t_leg = time.perf_counter()
            try:
                with tracer.span("fleet/leg", "serve", replica=rep.key, **targs):
                    if trace_id is not None:
                        # flow DEPARTURE, inside the leg slice so Perfetto
                        # anchors the arrow here; the replica's link_parent
                        # emits the matching arrival (same name/cat/id)
                        tracer.flow_start("fleet/leg", trace_flow_id(trace_id, seq),
                                          **targs)
                    logits = rep.client.predict(
                        image, priority=cls, deadline_ms=deadline_ms, request_id=rid,
                        trace_parent=tp, timeout_s=self._client_timeout_s,
                        model=model,
                    )
            except ClientConnectError as e:
                # the socket is dead — likely a killed replica: score it,
                # move the request to the next one (inference is pure)
                self._record_failure(rep, kind="connect")
                self._reg.counter("fleet.route_retries").inc()
                tried.add(rep.key)
                last_exc = e
                continue
            except ClientTimeout as e:
                # the READ timed out: a half-open socket, a response-eating
                # link, or a mid-flight blackhole. The request may have run
                # server-side — inference is pure, so the only duplicate
                # cost is wasted work — and surfacing a 504 for a fault the
                # fleet can absorb would break the partition-containment
                # contract: score the replica, re-route
                self._record_failure(rep, kind="timeout")
                self._reg.counter("fleet.route_retries").inc()
                tried.add(rep.key)
                last_exc = e
                continue
            except ClientHTTPError as e:
                if e.status == 503:
                    if e.retry_after is not None:
                        # backpressure: the replica is ALIVE, just saturated
                        # (breaker cooldown / brownout shed) — re-route, but
                        # never score its ejection counter: an overloaded
                        # replica and a dead one are different things
                        self._reg.counter("fleet.backpressure").inc()
                    else:
                        # unavailability with no comeback hint (draining,
                        # nothing routable behind it): score toward ejection
                        self._record_failure(rep, kind="http")
                    self._reg.counter("fleet.route_retries").inc()
                    tried.add(rep.key)
                    last_exc = e
                    continue
                # per-request verdict: pass through verbatim
                self._fail_leg(call, leg, e, cls=cls, trace_id=trace_id)
                return
            except ClientError as e:  # timeout: the request burned its budget
                self._fail_leg(call, leg, e, cls=cls, trace_id=trace_id)
                return
            leg_s = time.perf_counter() - t_leg
            with self._lock:
                rep.consecutive_failures = 0
                # per-replica latency estimate (the gray-failure signal):
                # per-LEG time, excluding router queueing — a backed-up
                # router must not make every replica look slow
                rep.lat_ewma_s = (
                    leg_s if rep.lat_ewma_s is None
                    else self._lat_alpha * leg_s + (1 - self._lat_alpha) * rep.lat_ewma_s
                )
            self._reg.histogram(f"{ROUTER_LATENCY}.{cls}").observe(time.perf_counter() - t0)
            self._reg.counter("fleet.routed").inc()
            if call.ok(leg, logits) and call.hedged:
                # a hedge RACE settled: record which leg won and where — the
                # flight recorder's per-request hedge outcome
                self._emit_event("hedge_outcome", winner=leg, replica=rep.key,
                                 trace=trace_id, cls=cls,
                                 leg_ms=round(leg_s * 1e3, 3))
            return
        self._fail_leg(call, leg, last_exc or NoHealthyReplicas("route attempts exhausted"),
                       cls=cls, trace_id=trace_id)

    # -- introspection (healthz / varz via the frontend) ---------------------

    def backends(self) -> list:
        """``(key, client)`` pairs for every registered backend — the
        federation scrape loop (obs/fleet.py) reuses the router's own
        keep-alive clients; ReplicaClient connections are per-thread, so a
        scrape thread never contends with route workers for a socket."""
        with self._lock:
            return [(r.key, r.client) for r in self._replicas.values()]

    def lease_ages(self) -> dict:
        """Per-replica seconds until lease expiry (None = static member, no
        lease) — a hang-report / federation info provider."""
        now = time.monotonic()
        with self._lock:
            return {r.key: (round(r.lease_until - now, 3) if r.lease_until is not None
                            else None)
                    for r in self._replicas.values()}

    def oldest_inflight(self) -> dict | None:
        """The longest-outstanding submitted request (age, class, rid) plus
        the in-flight count — what a hang report needs to say WHOSE request
        the wedged router is sitting on; None when idle."""
        now = time.perf_counter()
        with self._lock:
            if not self._inflight:
                return None
            token = min(self._inflight)
            rec = self._inflight[token]
            n = len(self._inflight)
        return {"age_s": round(now - rec["t0"], 3), "class": rec["cls"],
                "rid": rec["rid"], "inflight": n}

    def replicas_state(self) -> list[dict]:
        with self._lock:
            return [r.as_dict() for r in self._replicas.values()]

    def state(self) -> dict:
        """The frontend's /healthz payload: aggregate availability expressed
        in the breaker vocabulary (0 = serving, 1 = nothing routable -> 503)
        plus the per-replica fleet table."""
        reps = self.replicas_state()
        routable = sum(1 for r in reps if r["routable"])
        return {
            "breaker_state": 0 if routable else 1,
            "breaker": "closed" if routable else "open",
            "queued_total": sum(r["queue_depth"] for r in reps),
            "brownout": {
                "level": self._brownout_level,
                "shed_classes": sorted(self._shed_classes),
                "hedging": self._hedging_enabled,
            },
            "membership": {
                "static": sum(1 for r in reps if r["source"] == "static"),
                "leased": sum(1 for r in reps if r["source"] == "lease"),
                "lease_ttl_s": self._lease_ttl_s,
            },
            "fleet": {
                "total": len(reps), "routable": routable, "replicas": reps,
                # the union of advertised model names (None = zoo-unaware
                # fleet): what NoReplicaForModel's 503 body reports as served
                "models": sorted({m for r in reps if r["models"] for m in r["models"]}) or None,
            },
        }
