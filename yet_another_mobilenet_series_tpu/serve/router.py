"""Fleet router: weighted routing over N replica frontends.

One replica process is bounded by one host; the millions-of-users story
needs a shared-nothing fleet behind one address. The router is that
address. It speaks the SAME protocol the admission controller speaks
(``submit(image, priority, deadline_ms, ctx) -> Future`` + ``state()``), so
``serve/frontend.py`` can serve it directly — the fleet exposes the exact
endpoints, typed statuses, and ``X-Request-Id`` threading one replica does,
and a client cannot tell N replicas from one.

Routing policy, all driven by what the replicas THEMSELVES report:

- **health polling**: a daemon thread polls every backend's ``/healthz`` at
  ``poll_interval_s``. Each poll refreshes the replica's queue depth
  (``queued_total``), breaker state, draining flag, and identity block
  (``replica_id``/``pid``/``start_unix`` — a changed ``start_unix`` behind
  the same address is a detected restart, ``fleet.replica_restarts``).
- **weighted pick**: routable replicas are drawn with weight
  ``1 / (1 + queue_depth)`` (seeded RNG — reproducible in tests), so load
  skews away from backed-up replicas without starving anyone.
- **ejection / readmission**: ``eject_failures`` consecutive failures
  (poll or dispatch transport errors), an open breaker, or a draining flag
  eject a replica from rotation (``fleet.ejections``); the next healthy
  poll readmits it (``fleet.readmissions``). Ejection is advisory — with
  every replica ejected the router fails typed
  (:class:`NoHealthyReplicas` -> 503), never silently.
- **transport retry**: a dead socket (:class:`~.client.ClientConnectError`)
  or a replica-side 503 (draining / its own breaker) re-routes the request
  to the next replica (``fleet.route_retries``), because inference is pure;
  typed per-request verdicts (429 quota, 504 deadline, 500 engine error)
  pass through unchanged — the replica already ran ITS retry policy.
- **hedging** (serve/hedge.py): when a :class:`~.hedge.Hedger` is attached
  and >= 2 replicas are routable, a timer fires at the class's p99-derived
  bound and sends a duplicate to a second replica (primary's replica
  excluded); first answer wins, the loser is dropped idempotently.

Instrumentation: ``fleet.routed`` / ``fleet.route_retries`` /
``fleet.route_errors`` / ``fleet.ejections`` / ``fleet.readmissions`` /
``fleet.replica_restarts`` counters, the ``fleet.replicas_routable`` gauge,
per-class ``serve.router.latency_seconds.<class>`` histograms (the hedge
timer's input), and a ``fleet/route`` span per request.
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..utils.logging import emit
from .admission import CLASSES
from .client import ClientConnectError, ClientError, ClientHTTPError, ReplicaClient
from .hedge import ROUTER_LATENCY, HedgedCall, Hedger


class NoHealthyReplicas(RuntimeError):
    """Every replica is ejected or the backend set is empty: the fleet
    cannot serve this request (mapped to 503 by the frontend)."""


class _Replica:
    """Router-side view of one backend: client + polled health."""

    __slots__ = ("key", "host", "port", "client", "routable", "consecutive_failures",
                 "queue_depth", "breaker_state", "draining", "identity")

    def __init__(self, host: str, port: int, client):
        self.key = f"{host}:{port}"
        self.host = host
        self.port = port
        self.client = client
        self.routable = True
        self.consecutive_failures = 0
        self.queue_depth = 0.0
        self.breaker_state = 0
        self.draining = False
        self.identity: dict = {}

    def weight(self) -> float:
        return 1.0 / (1.0 + max(self.queue_depth, 0.0))

    def as_dict(self) -> dict:
        return {
            "key": self.key,
            "routable": self.routable,
            "queue_depth": self.queue_depth,
            "breaker_state": self.breaker_state,
            "draining": self.draining,
            "consecutive_failures": self.consecutive_failures,
            "identity": self.identity,
        }


class Router:
    """Weighted fleet router implementing the frontend's admission protocol."""

    def __init__(
        self,
        backends=(),
        *,
        default_class: str = "interactive",
        poll_interval_s: float = 0.25,
        eject_failures: int = 2,
        route_attempts: int = 3,
        client_timeout_s: float = 60.0,
        hedger: Hedger | None = None,
        seed: int = 0,
        max_workers: int = 32,
        client_factory=None,
    ):
        if default_class not in CLASSES:
            raise ValueError(f"default_class {default_class!r} not in {CLASSES}")
        self._default_class = default_class
        self._poll_interval_s = poll_interval_s
        self._eject_failures = max(1, int(eject_failures))
        self._route_attempts = max(1, int(route_attempts))
        self._client_timeout_s = client_timeout_s
        self._hedger = hedger
        self._rng = random.Random(seed)
        self._client_factory = client_factory or (
            lambda host, port: ReplicaClient(host, port, timeout_s=client_timeout_s)
        )
        self._lock = threading.Lock()
        self._replicas: dict[str, _Replica] = {}
        self._pool = ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="fleet-route")
        self._poll_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._reg = get_registry()
        self.set_backends(backends)

    # -- backend set (the supervisor / autoscaler mutate this) ---------------

    def set_backends(self, backends) -> None:
        """Reconcile the replica set against ``backends`` (iterable of
        ``(host, port)`` or ``"host:port"``). New backends start routable;
        removed backends have their clients closed."""
        want: dict[str, tuple[str, int]] = {}
        for b in backends:
            host, port = b.rsplit(":", 1) if isinstance(b, str) else b
            want[f"{host}:{int(port)}"] = (host, int(port))
        with self._lock:
            for key in [k for k in self._replicas if k not in want]:
                rep = self._replicas.pop(key)
                rep.client.close()
            for key, (host, port) in want.items():
                if key not in self._replicas:
                    self._replicas[key] = _Replica(host, port, self._client_factory(host, port))
            self._update_routable_gauge_locked()

    def _update_routable_gauge_locked(self) -> None:
        self._reg.gauge("fleet.replicas_routable").set(
            sum(1 for r in self._replicas.values() if r.routable)
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Router":
        if self._poll_thread is not None:
            raise RuntimeError("router already started")
        self._stop.clear()
        self._poll_thread = threading.Thread(target=self._poll_loop, name="fleet-poll", daemon=True)
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
            self._poll_thread = None
        self._pool.shutdown(wait=False, cancel_futures=True)
        with self._lock:
            for rep in self._replicas.values():
                rep.client.close()

    # -- health polling ------------------------------------------------------

    def _poll_loop(self) -> None:
        try:  # YAMT011: a silently-dead poll thread would freeze health state
            obs_trace.get_tracer().register_thread()
            while not self._stop.wait(self._poll_interval_s):
                self.poll_once()
        except Exception as e:  # noqa: BLE001 — contain, count, report
            self._reg.counter("serve.thread_crashes").inc()
            emit(f"[fleet] router poll thread crashed: {type(e).__name__}: {e}")

    def poll_once(self) -> None:
        """One health sweep over every backend (also callable directly —
        tests and the autoscaler use it for deterministic refreshes)."""
        with self._lock:
            reps = list(self._replicas.values())
        poll_timeout = max(2.0, 4 * self._poll_interval_s)
        for rep in reps:
            try:
                status, doc = rep.client.healthz(timeout_s=poll_timeout)
            except ClientError:
                self._record_failure(rep)
                continue
            identity = doc.get("replica") or {}
            with self._lock:
                rep.consecutive_failures = 0
                rep.queue_depth = float(doc.get("queued_total") or 0.0)
                rep.breaker_state = int(doc.get("breaker_state") or 0)
                rep.draining = bool(doc.get("draining"))
                if (identity and rep.identity
                        and identity.get("start_unix") != rep.identity.get("start_unix")):
                    # same address, new process: a supervisor restarted it
                    self._reg.counter("fleet.replica_restarts").inc()
                if identity:
                    rep.identity = identity
                healthy = status == 200 and not rep.draining
                self._set_routable_locked(rep, healthy)

    def _set_routable_locked(self, rep: _Replica, routable: bool) -> None:
        if routable and not rep.routable:
            rep.routable = True
            self._reg.counter("fleet.readmissions").inc()
        elif not routable and rep.routable:
            rep.routable = False
            self._reg.counter("fleet.ejections").inc()
        self._update_routable_gauge_locked()

    def _record_failure(self, rep: _Replica) -> None:
        with self._lock:
            rep.consecutive_failures += 1
            if rep.consecutive_failures >= self._eject_failures:
                self._set_routable_locked(rep, False)

    # -- picking -------------------------------------------------------------

    def _pick(self, exclude: set[str]) -> _Replica:
        with self._lock:
            pool = [r for r in self._replicas.values() if r.routable and r.key not in exclude]
            if not pool:
                raise NoHealthyReplicas(
                    f"no routable replica ({len(self._replicas)} registered, "
                    f"{len(exclude)} excluded)"
                )
            weights = [r.weight() for r in pool]
            return self._rng.choices(pool, weights=weights, k=1)[0]

    def set_hedger(self, hedger: Hedger | None) -> None:
        """Swap the hedging policy live (the serve_bench A/B drives both
        arms through ONE router so replica state is shared)."""
        self._hedger = hedger

    def n_routable(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values() if r.routable)

    def mean_queue_depth(self) -> float:
        """Mean polled queue depth across routable replicas (the
        autoscaler's backlog signal); 0 with nothing routable."""
        with self._lock:
            depths = [r.queue_depth for r in self._replicas.values() if r.routable]
        return sum(depths) / len(depths) if depths else 0.0

    # -- the serving protocol (what Frontend consumes) -----------------------

    def submit(self, image, *, priority: str | None = None,
               deadline_ms: float | None = None, ctx=None) -> Future:
        cls = priority or self._default_class
        if cls not in CLASSES:
            raise ValueError(f"unknown priority class {cls!r}; valid: {CLASSES}")
        fut: Future = Future()
        call = HedgedCall(fut)
        image = np.asarray(image, np.float32)
        # latency is measured from HERE (submit), not from leg start: router
        # queueing is part of what a client experiences, so the histogram
        # the autoscaler and hedge timer read must include it
        t_submit = time.perf_counter()
        self._pool.submit(self._route_guarded, call, image, cls, deadline_ms, ctx, t_submit)
        return fut

    def _route_guarded(self, call, image, cls, deadline_ms, ctx, t_submit) -> None:
        try:
            self._route(call, image, cls, deadline_ms, ctx, t_submit)
        except Exception as e:  # noqa: BLE001 — a crashed route must not hang its client
            self._reg.counter("fleet.route_errors").inc()
            call.err(HedgedCall.PRIMARY, e)

    def _route(self, call, image, cls, deadline_ms, ctx, t_submit) -> None:
        rid = ctx.wire_id if ctx is not None else None
        timer: threading.Timer | None = None
        primary_at: dict = {}
        hedge_s = self._hedger.timer_s(cls) if self._hedger is not None else None
        # the hedge timer arms at LEG start, while the histogram it derives
        # from measures submit -> resolution: under router-side overload the
        # timer inflates past per-leg latency, so hedging naturally backs
        # off instead of doubling the load of an already-saturated fleet
        if hedge_s is not None and self.n_routable() >= 2:
            timer = threading.Timer(
                hedge_s, self._fire_hedge,
                args=(call, image, cls, deadline_ms, rid, primary_at, t_submit),
            )
            timer.daemon = True
            timer.start()
        try:
            with obs_trace.get_tracer().span("fleet/route", "serve", cls=cls):
                self._leg(call, HedgedCall.PRIMARY, image, cls, deadline_ms, rid,
                          exclude=set(), chosen=primary_at, t_submit=t_submit)
        finally:
            if timer is not None and call.resolved:
                timer.cancel()

    def _fire_hedge(self, call, image, cls, deadline_ms, rid, primary_at, t_submit) -> None:
        try:  # Timer threads die as silently as any other (YAMT011 discipline)
            if not call.launch_hedge():
                return  # primary already resolved; nothing to duplicate
            exclude = {primary_at["key"]} if "key" in primary_at else set()
            self._leg(call, HedgedCall.HEDGE, image, cls, deadline_ms, rid,
                      exclude=exclude, t_submit=t_submit)
        except Exception as e:  # noqa: BLE001 — contain: fail the leg, not the thread
            self._reg.counter("fleet.route_errors").inc()
            call.err(HedgedCall.HEDGE, e)

    def _leg(self, call, leg, image, cls, deadline_ms, rid, *, exclude, chosen=None,
             t_submit=None) -> None:
        """One leg (primary or hedge) of one request: pick, dispatch, retry
        transport-level failures on other replicas, resolve the call."""
        tried = set(exclude)
        last_exc: Exception | None = None
        for _ in range(self._route_attempts):
            try:
                rep = self._pick(tried)
            except NoHealthyReplicas as e:
                call.err(leg, last_exc or e)
                return
            if chosen is not None:
                chosen["key"] = rep.key
            t0 = time.perf_counter() if t_submit is None else t_submit
            try:
                logits = rep.client.predict(
                    image, priority=cls, deadline_ms=deadline_ms, request_id=rid,
                    timeout_s=self._client_timeout_s,
                )
            except ClientConnectError as e:
                # the socket is dead — likely a killed replica: score it,
                # move the request to the next one (inference is pure)
                self._record_failure(rep)
                self._reg.counter("fleet.route_retries").inc()
                tried.add(rep.key)
                last_exc = e
                continue
            except ClientHTTPError as e:
                if e.status == 503:
                    # replica-local unavailability (draining / its breaker):
                    # another replica may well serve it
                    self._reg.counter("fleet.route_retries").inc()
                    tried.add(rep.key)
                    last_exc = e
                    continue
                call.err(leg, e)  # per-request verdict: pass through verbatim
                return
            except ClientError as e:  # timeout: the request burned its budget
                call.err(leg, e)
                return
            with self._lock:
                rep.consecutive_failures = 0
            self._reg.histogram(f"{ROUTER_LATENCY}.{cls}").observe(time.perf_counter() - t0)
            self._reg.counter("fleet.routed").inc()
            call.ok(leg, logits)
            return
        call.err(leg, last_exc or NoHealthyReplicas("route attempts exhausted"))

    # -- introspection (healthz / varz via the frontend) ---------------------

    def replicas_state(self) -> list[dict]:
        with self._lock:
            return [r.as_dict() for r in self._replicas.values()]

    def state(self) -> dict:
        """The frontend's /healthz payload: aggregate availability expressed
        in the breaker vocabulary (0 = serving, 1 = nothing routable -> 503)
        plus the per-replica fleet table."""
        reps = self.replicas_state()
        routable = sum(1 for r in reps if r["routable"])
        return {
            "breaker_state": 0 if routable else 1,
            "breaker": "closed" if routable else "open",
            "queued_total": sum(r["queue_depth"] for r in reps),
            "fleet": {"total": len(reps), "routable": routable, "replicas": reps},
        }
