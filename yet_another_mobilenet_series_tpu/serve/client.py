"""Connection-reused HTTP client for one serving replica — the client half
of the front door, extracted so every caller that speaks to a frontend
(the fleet router, the hedger's duplicate leg, benches, tests) shares ONE
implementation of the wire protocol instead of three divergent
urllib-request copies.

Design points, matching the frontend's contract (serve/frontend.py):

- **connection reuse**: the frontend speaks HTTP/1.1 with Content-Length on
  every response, so keep-alive works; the client holds one persistent
  ``http.client.HTTPConnection`` PER THREAD (the router's worker pool and
  the poll thread each get their own socket — ``http.client`` connections
  are not thread-safe). A stale keep-alive socket (server closed it between
  requests) is retried ONCE on a fresh connection; a failure on the fresh
  socket is a real :class:`ClientConnectError`. The connection table prunes
  a thread's replaced socket and entries left by exited threads (hedge
  Timer threads are transient), so a long-lived router against a flapping
  replica holds a bounded socket set.
- **split timeouts**: ``connect_timeout_s`` bounds the TCP handshake
  SEPARATELY from ``timeout_s`` (the read bound). Across real hosts the
  failure modes differ: a crashed replica refuses instantly, but a
  PARTITIONED one drops SYNs on the floor — with one shared timeout every
  routing probe into a blackhole burns the full read budget. A connect
  that cannot complete inside ``connect_timeout_s`` raises
  :class:`ClientConnectError` (the request never left this host — retry
  another replica immediately; counted ``serve.client.connect_timeouts``),
  while a read-timeout is :class:`ClientTimeout` (the request may be
  running server-side — half-open sockets and response-eating links
  surface HERE, bounded, instead of wedging a worker).
- **typed errors**: every non-2xx response raises :class:`ClientHTTPError`
  carrying the HTTP status and the frontend's wire error tag
  (``queue_full``, ``breaker_open``, ...), so the router can pass a
  replica's typed rejection through to ITS client unchanged — a fleet is
  externally indistinguishable from one replica. Transport-level failures
  are :class:`ClientConnectError` (dead/refused/reset/unreachable socket —
  the retry-on-another-replica signal) or :class:`ClientTimeout` (the read
  timeout expired with the request possibly still running server-side).
- **identity threading**: ``predict(..., request_id=...)`` sends
  ``X-Request-Id``, so a router-minted id correlates the replica-side spans
  with the router's own ``fleet/route`` span.
- **membership**: :meth:`register` / :meth:`deregister` speak the router's
  TTL-lease admin endpoints (``POST /register``), the transport half of
  the multi-host membership story — a replica heartbeats its own address
  into the fleet and expires out when it stops.

Images ride as raw bytes + ``X-Shape`` and ``X-Dtype`` headers: ``f4``
(little-endian float32, the historical contract and the default) or ``u8``
(raw uint8 pixels — the quantized wire, 4x fewer bytes per request, which
this header lets ride router->replica across the fleet).
"""

from __future__ import annotations

import http.client
import json
import socket
import threading

import numpy as np

from ..obs.registry import get_registry

DEFAULT_TIMEOUT_S = 60.0

# wire dtype codes (X-Dtype header) <-> numpy dtypes; "f4" is the default
# when the header is absent (pre-header clients keep working)
WIRE_DTYPES = {"f4": np.dtype("<f4"), "u8": np.dtype("u1")}


def wire_dtype_code(dtype) -> str:
    """The X-Dtype code for an array dtype: uint8 rides as ``u8``, anything
    else is coerced to the ``f4`` contract by the sender."""
    return "u8" if np.dtype(dtype) == np.dtype("u1") else "f4"


class ClientError(RuntimeError):
    """Base class for every typed client failure."""


class ClientConnectError(ClientError):
    """The replica's socket is dead: connection refused, reset, or closed
    mid-request. The caller may safely retry ANOTHER replica — inference is
    pure and the request either never arrived or its answer is orphaned."""


class ClientTimeout(ClientError):
    """The socket timeout expired. Unlike a connect error the request may
    still be running server-side; retries must be idempotence-aware (they
    are: inference is pure)."""


class ClientHTTPError(ClientError):
    """A non-2xx response with the frontend's typed error body. ``status``
    and ``tag`` mirror the wire (``429``/``queue_full``, ``503``/
    ``breaker_open``, ...), so routers re-raise replica verdicts verbatim.
    ``retry_after`` carries the response's ``Retry-After`` seconds when the
    server sent one — the backpressure signal the router uses to tell an
    overloaded-but-healthy replica (do NOT eject) from a dead one."""

    def __init__(self, status: int, tag: str, message: str,
                 retry_after: float | None = None):
        super().__init__(f"{status} {tag}: {message}")
        self.status = status
        self.tag = tag
        self.retry_after = retry_after


class _ConnectTimeout(OSError):
    """Internal marker: the TCP handshake itself timed out (a blackholed
    address). Distinct from a read timeout — the request never left this
    host, so the caller may retry another replica with zero idempotence
    concern. Mapped to :class:`ClientConnectError` by ``_request``."""


class _SplitTimeoutConnection(http.client.HTTPConnection):
    """HTTPConnection whose CONNECT phase is bounded separately from reads:
    ``socket.create_connection`` runs under ``connect_timeout``, then the
    established socket switches to the (longer) read timeout. With the
    stdlib's single ``timeout`` a probe into a SYN-blackhole burns the full
    read budget before failing."""

    def __init__(self, host, port, *, timeout, connect_timeout):
        super().__init__(host, port, timeout=timeout)
        self.connect_timeout = connect_timeout

    def connect(self):
        try:
            self.sock = socket.create_connection(
                (self.host, self.port), self.connect_timeout
            )
        except TimeoutError as e:  # socket.timeout: the handshake hung
            raise _ConnectTimeout(
                f"connect to {self.host}:{self.port} exceeded {self.connect_timeout:.1f}s"
            ) from e
        self.sock.settimeout(self.timeout)  # reads run on the full budget
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        if self._tunnel_host:
            self._tunnel()


def _parse_retry_after(headers: dict) -> float | None:
    """Seconds from a ``Retry-After`` header; None when absent or not the
    delta-seconds form (the HTTP-date form is never emitted by our
    frontend, so it is not worth a date parser here)."""
    raw = headers.get("Retry-After")
    if raw is None:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


class ReplicaClient:
    """Typed, keep-alive HTTP client for one frontend address."""

    def __init__(self, host: str, port: int, *, timeout_s: float = DEFAULT_TIMEOUT_S,
                 connect_timeout_s: float | None = None):
        self.host = host
        self.port = int(port)
        self.timeout_s = timeout_s
        # None = the pre-split behavior (connect shares the read budget);
        # routers pass a tight bound so blackholes fail in ~a poll interval
        self.connect_timeout_s = timeout_s if connect_timeout_s is None else connect_timeout_s
        self._local = threading.local()
        # one live connection per thread ident, for close(); threads come
        # and go (Timer threads in the hedger), so the local alone cannot
        # enumerate — and a plain ever-grown list would leak one socket per
        # reconnect against a flapping replica
        self._conns: dict[int, http.client.HTTPConnection] = {}
        self._conns_lock = threading.Lock()

    @classmethod
    def from_addr(cls, addr: dict, **kw) -> "ReplicaClient":
        """Build from a ``listen_addr.json`` dict (``{"host", "port"}``)."""
        return cls(addr["host"], addr["port"], **kw)

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- transport ----------------------------------------------------------

    def _fresh_conn(self, timeout_s: float) -> http.client.HTTPConnection:
        conn = _SplitTimeoutConnection(
            self.host, self.port, timeout=timeout_s,
            connect_timeout=min(self.connect_timeout_s, timeout_s),
        )
        ident = threading.get_ident()
        with self._conns_lock:
            # prune on replacement (this thread's old socket) and entries
            # left behind by exited threads: the table stays bounded by the
            # LIVE thread count however often the replica flaps
            old = self._conns.pop(ident, None)
            live = {t.ident for t in threading.enumerate()}
            dead = [k for k in self._conns if k not in live]
            stale = [self._conns.pop(k) for k in dead]
            self._conns[ident] = conn
        if old is not None:
            old.close()
        for c in stale:
            c.close()
        return conn

    def _request(self, method: str, path: str, body: bytes | None = None,
                 headers: dict | None = None, timeout_s: float | None = None):
        """(status, response headers, body bytes); one stale-socket retry."""
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        last_exc: Exception | None = None
        for attempt in (0, 1):
            conn = getattr(self._local, "conn", None)
            if conn is None or attempt == 1:
                if conn is not None:
                    conn.close()
                conn = self._fresh_conn(timeout_s)
                self._local.conn = conn
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
            try:
                conn.request(method, path, body=body, headers=headers or {})
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, dict(resp.headers), data
            except _ConnectTimeout as e:
                # the handshake itself hung: a blackholed/partitioned
                # address. Conclusive — the handshake ran on a fresh socket,
                # so the stale-keep-alive retry proves nothing; fail fast so
                # the router re-routes within the CONNECT budget, not the
                # read budget
                get_registry().counter("serve.client.connect_timeouts").inc()
                conn.close()
                self._local.conn = None
                raise ClientConnectError(
                    f"{method} {self.base_url}{path}: {e}"
                ) from e
            except socket.timeout as e:
                conn.close()
                self._local.conn = None
                raise ClientTimeout(
                    f"{method} {self.base_url}{path} exceeded {timeout_s:.1f}s"
                ) from e
            except (ConnectionError, BrokenPipeError, http.client.HTTPException, OSError) as e:
                # a reused socket the server already closed fails here; only
                # the retry on a FRESH socket proves the replica is dead
                conn.close()
                self._local.conn = None
                last_exc = e
        raise ClientConnectError(
            f"{method} {self.base_url}{path}: {type(last_exc).__name__}: {last_exc}"
        ) from last_exc

    def _request_json(self, method: str, path: str, **kw):
        status, headers, data = self._request(method, path, **kw)
        try:
            doc = json.loads(data) if data else {}
        except json.JSONDecodeError:
            doc = {"error": "bad_body", "message": data[:200].decode("utf-8", "replace")}
        return status, headers, doc

    # -- the serving protocol ------------------------------------------------

    def predict(self, image: np.ndarray, *, priority: str | None = None,
                deadline_ms: float | None = None, request_id: str | None = None,
                trace_parent: str | None = None,
                timeout_s: float | None = None,
                model: str | None = None) -> np.ndarray:
        """POST one (H, W, C) image; returns the logits row. Raises the
        typed hierarchy above on every failure mode. A uint8 array rides
        the wire RAW (``X-Dtype: u8`` — the quantized wire's 4x byte drop
        crosses the fleet instead of being silently upcast); anything else
        is coerced to the little-endian float32 contract. ``model`` names
        the zoo tenant (``X-Model`` header); None = the replica's default
        model. An unserved name comes back as a typed 400
        (``unknown_model`` — :class:`ClientHTTPError` with that tag, the
        served-model list riding in the error body)."""
        image = np.asarray(image)
        code = wire_dtype_code(image.dtype)
        image = np.ascontiguousarray(image, dtype=WIRE_DTYPES[code])
        headers = {
            "Content-Type": "application/octet-stream",
            "X-Shape": ",".join(str(d) for d in image.shape),
            "X-Dtype": code,
        }
        if priority:
            headers["X-Priority"] = priority
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(deadline_ms)
        if request_id:
            headers["X-Request-Id"] = str(request_id)
        if trace_parent:
            # fleet trace propagation (serve/context.py parse_trace_parent):
            # "<trace_id>-<seq>-<leg>", stamped per leg by the router so the
            # replica's trace events carry the fleet-level request id
            headers["X-Trace-Parent"] = str(trace_parent)
        if model:
            headers["X-Model"] = str(model)
        status, resp_headers, doc = self._request_json(
            "POST", "/predict", body=image.tobytes(), headers=headers, timeout_s=timeout_s
        )
        if status != 200:
            raise ClientHTTPError(status, doc.get("error", "unknown"), doc.get("message", ""),
                                  retry_after=_parse_retry_after(resp_headers))
        return np.asarray(doc["logits"], np.float32)

    def register(self, host: str, port: int, *, ttl_s: float,
                 replica_id: str = "", timeout_s: float | None = None,
                 models: dict | None = None) -> dict:
        """POST /register: announce (or heartbeat-renew) a replica address
        with a TTL lease on a router frontend. ``models`` is the served-
        model advertisement (``{name: digest}``) driving the router's
        model-aware placement. Returns the router's lease verdict
        (``{"ok", "ttl_s", ...}``); raises :class:`ClientHTTPError` when
        the target is not a router (404), rejects the body (400), or
        refuses a conflicting model digest (409, ``digest_conflict``)."""
        payload = {"host": host, "port": int(port), "ttl_s": ttl_s,
                   "replica_id": replica_id}
        if models is not None:
            payload["models"] = dict(models)
        body = json.dumps(payload).encode()
        status, _, doc = self._request_json(
            "POST", "/register", body=body,
            headers={"Content-Type": "application/json"}, timeout_s=timeout_s,
        )
        if status != 200:
            raise ClientHTTPError(status, doc.get("error", "unknown"), doc.get("message", ""))
        return doc

    def deregister(self, host: str, port: int, *, timeout_s: float | None = None) -> dict:
        """POST /deregister: drop a leased membership before its TTL runs
        out (the clean-drain half of the lease lifecycle)."""
        body = json.dumps({"host": host, "port": int(port)}).encode()
        status, _, doc = self._request_json(
            "POST", "/deregister", body=body,
            headers={"Content-Type": "application/json"}, timeout_s=timeout_s,
        )
        if status != 200:
            raise ClientHTTPError(status, doc.get("error", "unknown"), doc.get("message", ""))
        return doc

    def healthz(self, timeout_s: float | None = None) -> tuple[int, dict]:
        """(status, body) — 503 is a VALUE here (breaker open / draining),
        not an exception; only transport failures raise."""
        status, _, doc = self._request_json("GET", "/healthz", timeout_s=timeout_s)
        return status, doc

    def varz(self, timeout_s: float | None = None) -> tuple[int, dict]:
        status, _, doc = self._request_json("GET", "/varz", timeout_s=timeout_s)
        return status, doc

    def metrics_text(self, timeout_s: float | None = None) -> str:
        status, _, data = self._request("GET", "/metrics", timeout_s=timeout_s)
        if status != 200:
            raise ClientHTTPError(status, "metrics", data[:200].decode("utf-8", "replace"))
        return data.decode()

    def close(self) -> None:
        with self._conns_lock:
            conns, self._conns = list(self._conns.values()), {}
        for c in conns:
            c.close()
