"""Connection-reused HTTP client for one serving replica — the client half
of the front door, extracted so every caller that speaks to a frontend
(the fleet router, the hedger's duplicate leg, benches, tests) shares ONE
implementation of the wire protocol instead of three divergent
urllib-request copies.

Design points, matching the frontend's contract (serve/frontend.py):

- **connection reuse**: the frontend speaks HTTP/1.1 with Content-Length on
  every response, so keep-alive works; the client holds one persistent
  ``http.client.HTTPConnection`` PER THREAD (the router's worker pool and
  the poll thread each get their own socket — ``http.client`` connections
  are not thread-safe). A stale keep-alive socket (server closed it between
  requests) is retried ONCE on a fresh connection; a failure on the fresh
  socket is a real :class:`ClientConnectError`.
- **typed errors**: every non-2xx response raises :class:`ClientHTTPError`
  carrying the HTTP status and the frontend's wire error tag
  (``queue_full``, ``breaker_open``, ...), so the router can pass a
  replica's typed rejection through to ITS client unchanged — a fleet is
  externally indistinguishable from one replica. Transport-level failures
  are :class:`ClientConnectError` (dead/refused/reset socket — the retry-
  on-another-replica signal) or :class:`ClientTimeout` (the socket timeout
  expired with the request possibly still running server-side).
- **identity threading**: ``predict(..., request_id=...)`` sends
  ``X-Request-Id``, so a router-minted id correlates the replica-side spans
  with the router's own ``fleet/route`` span.

Images ride as raw little-endian float32 bytes + ``X-Shape`` (the
octet-stream body the frontend parses without JSON overhead).
"""

from __future__ import annotations

import http.client
import json
import socket
import threading

import numpy as np

DEFAULT_TIMEOUT_S = 60.0


class ClientError(RuntimeError):
    """Base class for every typed client failure."""


class ClientConnectError(ClientError):
    """The replica's socket is dead: connection refused, reset, or closed
    mid-request. The caller may safely retry ANOTHER replica — inference is
    pure and the request either never arrived or its answer is orphaned."""


class ClientTimeout(ClientError):
    """The socket timeout expired. Unlike a connect error the request may
    still be running server-side; retries must be idempotence-aware (they
    are: inference is pure)."""


class ClientHTTPError(ClientError):
    """A non-2xx response with the frontend's typed error body. ``status``
    and ``tag`` mirror the wire (``429``/``queue_full``, ``503``/
    ``breaker_open``, ...), so routers re-raise replica verdicts verbatim.
    ``retry_after`` carries the response's ``Retry-After`` seconds when the
    server sent one — the backpressure signal the router uses to tell an
    overloaded-but-healthy replica (do NOT eject) from a dead one."""

    def __init__(self, status: int, tag: str, message: str,
                 retry_after: float | None = None):
        super().__init__(f"{status} {tag}: {message}")
        self.status = status
        self.tag = tag
        self.retry_after = retry_after


def _parse_retry_after(headers: dict) -> float | None:
    """Seconds from a ``Retry-After`` header; None when absent or not the
    delta-seconds form (the HTTP-date form is never emitted by our
    frontend, so it is not worth a date parser here)."""
    raw = headers.get("Retry-After")
    if raw is None:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


class ReplicaClient:
    """Typed, keep-alive HTTP client for one frontend address."""

    def __init__(self, host: str, port: int, *, timeout_s: float = DEFAULT_TIMEOUT_S):
        self.host = host
        self.port = int(port)
        self.timeout_s = timeout_s
        self._local = threading.local()
        # every connection ever opened, for close(); threads come and go
        # (Timer threads in the hedger), so the local alone cannot enumerate
        self._conns: list[http.client.HTTPConnection] = []
        self._conns_lock = threading.Lock()

    @classmethod
    def from_addr(cls, addr: dict, **kw) -> "ReplicaClient":
        """Build from a ``listen_addr.json`` dict (``{"host", "port"}``)."""
        return cls(addr["host"], addr["port"], **kw)

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- transport ----------------------------------------------------------

    def _fresh_conn(self, timeout_s: float) -> http.client.HTTPConnection:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=timeout_s)
        with self._conns_lock:
            self._conns.append(conn)
        return conn

    def _request(self, method: str, path: str, body: bytes | None = None,
                 headers: dict | None = None, timeout_s: float | None = None):
        """(status, response headers, body bytes); one stale-socket retry."""
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        last_exc: Exception | None = None
        for attempt in (0, 1):
            conn = getattr(self._local, "conn", None)
            if conn is None or attempt == 1:
                if conn is not None:
                    conn.close()
                conn = self._fresh_conn(timeout_s)
                self._local.conn = conn
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
            try:
                conn.request(method, path, body=body, headers=headers or {})
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, dict(resp.headers), data
            except socket.timeout as e:
                conn.close()
                self._local.conn = None
                raise ClientTimeout(
                    f"{method} {self.base_url}{path} exceeded {timeout_s:.1f}s"
                ) from e
            except (ConnectionError, BrokenPipeError, http.client.HTTPException, OSError) as e:
                # a reused socket the server already closed fails here; only
                # the retry on a FRESH socket proves the replica is dead
                conn.close()
                self._local.conn = None
                last_exc = e
        raise ClientConnectError(
            f"{method} {self.base_url}{path}: {type(last_exc).__name__}: {last_exc}"
        ) from last_exc

    def _request_json(self, method: str, path: str, **kw):
        status, headers, data = self._request(method, path, **kw)
        try:
            doc = json.loads(data) if data else {}
        except json.JSONDecodeError:
            doc = {"error": "bad_body", "message": data[:200].decode("utf-8", "replace")}
        return status, headers, doc

    # -- the serving protocol ------------------------------------------------

    def predict(self, image: np.ndarray, *, priority: str | None = None,
                deadline_ms: float | None = None, request_id: str | None = None,
                timeout_s: float | None = None) -> np.ndarray:
        """POST one (H, W, C) image; returns the logits row. Raises the
        typed hierarchy above on every failure mode."""
        image = np.ascontiguousarray(image, dtype="<f4")
        headers = {
            "Content-Type": "application/octet-stream",
            "X-Shape": ",".join(str(d) for d in image.shape),
        }
        if priority:
            headers["X-Priority"] = priority
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(deadline_ms)
        if request_id:
            headers["X-Request-Id"] = str(request_id)
        status, resp_headers, doc = self._request_json(
            "POST", "/predict", body=image.tobytes(), headers=headers, timeout_s=timeout_s
        )
        if status != 200:
            raise ClientHTTPError(status, doc.get("error", "unknown"), doc.get("message", ""),
                                  retry_after=_parse_retry_after(resp_headers))
        return np.asarray(doc["logits"], np.float32)

    def healthz(self, timeout_s: float | None = None) -> tuple[int, dict]:
        """(status, body) — 503 is a VALUE here (breaker open / draining),
        not an exception; only transport failures raise."""
        status, _, doc = self._request_json("GET", "/healthz", timeout_s=timeout_s)
        return status, doc

    def varz(self, timeout_s: float | None = None) -> tuple[int, dict]:
        status, _, doc = self._request_json("GET", "/varz", timeout_s=timeout_s)
        return status, doc

    def metrics_text(self, timeout_s: float | None = None) -> str:
        status, _, data = self._request("GET", "/metrics", timeout_s=timeout_s)
        if status != 200:
            raise ClientHTTPError(status, "metrics", data[:200].decode("utf-8", "replace"))
        return data.decode()

    def close(self) -> None:
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            c.close()
