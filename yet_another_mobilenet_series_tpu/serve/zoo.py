"""Multi-model zoo: N named InferenceBundles behind one serving process.

Every process used to serve exactly one bundle; the ROADMAP's north star
(efficientnet_b0 + mobilenet_v3_small + AtomNAS-searched exports behind one
front door, FLASH/LANA-style cheap-model-first cascading as the dominant
cost lever) needs a **zoo**: one engine holding several named models, each
with its own AOT ladder keyed ``(model, bucket, image_size, K)``
(serve/engine.py) while sharing the slot pool, the dispatch path, and the
admission edge (per-model quotas, serve/admission.py).

:class:`ModelZoo` is the configuration spine of that subsystem: it loads
and names the bundles from a ``serve.zoo`` config block
(config.ZooConfig), resolves the default tenant, carries per-model quotas
and image-size ladders, and produces the kwargs the engine, the admission
controller, and the lease registration each need. The ON-WIRE identity is
the ``X-Model`` header (serve/frontend.py -> RequestContext.model ->
batcher (model, shape) grouping -> engine tenant dispatch); the FLEET
identity is the lease advertisement ``{model_name: digest}``
(:meth:`lease_models`), which the router uses for model-aware placement
(route only to replicas advertising the request's model) and for the
mixed-version refusal: two replicas claiming one model name with different
content digests (serve/export.py ``bundle_digest``) is a registration
error, not a silent lottery over which weights answer.

Config spec grammar (all plain strings so they ride ``--serve.zoo.*``
CLI overrides; see config.ZooConfig):

- ``models``:       ``"small=/b/small,big=/b/big"`` — name=bundle-dir pairs
- ``placement``:    ``"small|big;big"`` — ';'-separated per-slot groups of
                    '|'-joined names; fleet slot i serves group
                    ``i % len(groups)`` (cli/fleet.py spawns each slot with
                    a models= subset override)
- ``quotas``:       ``"small=64,big=16"`` — per-model in-system caps
- ``image_sizes``:  ``"small=160|192,big=224"`` — per-model warm ladders

This module is import-light (no jax at module scope): the jax-free fleet
supervisor (cli/fleet.py) uses the parsers for placement without paying —
or breaking on — a jax import; bundle loading is deferred to
:meth:`ModelZoo.from_config`.
"""

from __future__ import annotations

from typing import Mapping, Sequence


def _valid_name(name: str) -> bool:
    return bool(name) and name.replace("-", "").replace("_", "").isalnum()


def parse_models(spec: str) -> dict[str, str]:
    """``"small=/b/small,big=/b/big"`` -> ``{"small": "/b/small", ...}``.
    Names must be ``[A-Za-z0-9_-]`` (they become metric-family components);
    duplicates and empty entries are errors, order is preserved (the first
    name is the default tenant unless ``zoo.default`` overrides)."""
    out: dict[str, str] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, sep, path = part.partition("=")
        name, path = name.strip(), path.strip()
        if not sep or not path:
            raise ValueError(f"zoo.models entry {part!r} is not name=/bundle/dir")
        if not _valid_name(name):
            raise ValueError(f"zoo model name {name!r} must be non-empty [A-Za-z0-9_-]")
        if name in out:
            raise ValueError(f"zoo model {name!r} named twice")
        out[name] = path
    return out


def parse_placement(spec: str, models: Sequence[str]) -> list[tuple[str, ...]]:
    """``"small|big;big"`` -> ``[("small", "big"), ("big",)]``. Every name
    must be a configured model, every configured model must appear in at
    least one group (an unplaced model would be unroutable), and no group
    may be empty. Empty spec -> one group serving everything (no sharding)."""
    models = tuple(models)
    if not spec.strip():
        return [models]
    groups: list[tuple[str, ...]] = []
    for chunk in spec.split(";"):
        names = tuple(n.strip() for n in chunk.split("|") if n.strip())
        if not names:
            raise ValueError(f"zoo.placement has an empty slot group in {spec!r}")
        for n in names:
            if n not in models:
                raise ValueError(f"zoo.placement names unknown model {n!r}; configured: {models}")
        groups.append(names)
    placed = {n for g in groups for n in g}
    missing = [m for m in models if m not in placed]
    if missing:
        raise ValueError(f"zoo.placement leaves {missing} on no slot — they would be unroutable")
    return groups


def slot_models(groups: Sequence[Sequence[str]], slot: int) -> tuple[str, ...]:
    """The model subset fleet slot ``slot`` serves: placement groups repeat
    cyclically over slots, so 2 groups on a 4-replica fleet give each group
    two replicas."""
    return tuple(groups[slot % len(groups)])


def slot_overrides(zc, slot: int) -> list[str]:
    """The per-slot replica argv overrides cli/fleet.py appends under
    model-sharded placement: the slot's ``models=`` subset, with quotas /
    image_sizes / default filtered to it (a replica config naming a model
    it does not load is a validation error by design) and ``placement``
    cleared (a replica serves its whole assignment)."""
    paths = parse_models(zc.models)
    groups = parse_placement(zc.placement, list(paths))
    names = slot_models(groups, slot)
    quotas = {n: v for n, v in parse_quotas(zc.quotas).items() if n in names}
    sizes = {n: v for n, v in parse_image_sizes(zc.image_sizes).items() if n in names}
    default = zc.default if zc.default in names else names[0]
    return [
        "serve.zoo.models=" + ",".join(f"{n}={paths[n]}" for n in names),
        "serve.zoo.placement=",
        f"serve.zoo.default={default}",
        "serve.zoo.quotas=" + ",".join(f"{n}={v}" for n, v in quotas.items()),
        "serve.zoo.image_sizes=" + ",".join(
            f"{n}=" + "|".join(str(s) for s in v) for n, v in sizes.items()),
    ]


def _parse_per_model(spec: str, what: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, sep, val = part.partition("=")
        name, val = name.strip(), val.strip()
        if not sep or not val:
            raise ValueError(f"zoo.{what} entry {part!r} is not name=value")
        if name in out:
            raise ValueError(f"zoo.{what} names {name!r} twice")
        out[name] = val
    return out


def parse_quotas(spec: str) -> dict[str, int]:
    """``"small=64,big=16"`` -> per-model in-system caps (admission)."""
    out = {}
    for name, val in _parse_per_model(spec, "quotas").items():
        quota = int(val)
        if quota < 1:
            raise ValueError(f"zoo quota for {name!r} must be >= 1, got {quota}")
        out[name] = quota
    return out


def parse_image_sizes(spec: str) -> dict[str, tuple[int, ...]]:
    """``"small=160|192,big=224"`` -> per-model warm image-size ladders."""
    out = {}
    for name, val in _parse_per_model(spec, "image_sizes").items():
        sizes = tuple(sorted({int(s) for s in val.split("|") if s.strip()}))
        if not sizes or sizes[0] < 1:
            raise ValueError(f"zoo image sizes for {name!r} must be positive, got {val!r}")
        out[name] = sizes
    return out


class ModelZoo:
    """The loaded tenant set of one serving process.

    Holds name -> :class:`~.export.InferenceBundle`, the default tenant,
    per-model quotas and image-size ladders, and each bundle's content
    digest. The engine/admission/lease layers each take their slice via
    :meth:`engine_kwargs` / :meth:`admission_kwargs` / :meth:`lease_models`
    — the zoo is configuration, not a dispatch path.
    """

    def __init__(
        self,
        bundles: Mapping[str, "object"],
        *,
        default: str | None = None,
        quotas: Mapping[str, int] | None = None,
        image_sizes: Mapping[str, Sequence[int]] | None = None,
    ):
        if not bundles:
            raise ValueError("a ModelZoo needs at least one model")
        for name in bundles:
            if not _valid_name(name):
                raise ValueError(f"zoo model name {name!r} must be non-empty [A-Za-z0-9_-]")
        self._bundles = dict(bundles)
        self._default = default or next(iter(self._bundles))
        if self._default not in self._bundles:
            raise ValueError(
                f"zoo.default {self._default!r} not among models {tuple(self._bundles)}")
        for scope, mapping in (("quotas", quotas), ("image_sizes", image_sizes)):
            for name in (mapping or {}):
                if name not in self._bundles:
                    raise ValueError(f"zoo.{scope} names unknown model {name!r}")
        self._quotas = dict(quotas or {})
        self._image_sizes = {k: tuple(v) for k, v in (image_sizes or {}).items()}

    # -- identity ------------------------------------------------------------

    @property
    def models(self) -> tuple[str, ...]:
        return tuple(self._bundles)

    @property
    def default(self) -> str:
        return self._default

    @property
    def bundles(self) -> dict:
        return dict(self._bundles)

    def bundle(self, name: str):
        return self._bundles[name]

    def digests(self) -> dict[str, str | None]:
        """name -> stamped content digest (None for a pre-zoo bundle)."""
        return {name: b.meta.get("digest") for name, b in self._bundles.items()}

    # -- per-layer kwargs ----------------------------------------------------

    def engine_kwargs(self) -> dict:
        """The multi-tenant slice of InferenceEngine's constructor."""
        return {
            "models": dict(self._bundles),
            "default_model": self._default,
            "model_image_sizes": dict(self._image_sizes),
        }

    def admission_kwargs(self) -> dict:
        """The zoo slice of AdmissionController.from_config."""
        return {
            "models": self.models,
            "default_model": self._default,
            "model_quotas": dict(self._quotas),
        }

    def lease_models(self) -> dict[str, str]:
        """The lease advertisement: name -> digest ('' when unstamped). The
        router keys placement on the names and refuses a registration whose
        digest conflicts with another live replica's for the same name."""
        return {name: (d or "") for name, d in self.digests().items()}

    # -- construction --------------------------------------------------------

    @classmethod
    def from_config(cls, zc) -> "ModelZoo":
        """Load a zoo from a config.ZooConfig block. Each bundle loads (and
        digest-verifies, serve/export.py) from its directory; a bundle
        stamped with a model_name DIFFERENT from its configured name is
        refused — an alias pointing at the wrong artifact is exactly the
        identity confusion the stamp exists to catch."""
        from .export import load_bundle  # deferred: keeps this module jax-free

        paths = parse_models(zc.models)
        if not paths:
            raise ValueError("serve.zoo.models is empty; nothing to serve")
        bundles = {}
        for name, path in paths.items():
            b = load_bundle(path)
            stamped = b.meta.get("model_name")
            if stamped is not None and stamped != name:
                raise ValueError(
                    f"bundle at {path!r} is stamped model_name={stamped!r} but configured "
                    f"as {name!r}; aliasing a bundle across names defeats the digest identity"
                )
            bundles[name] = b
        return cls(
            bundles,
            default=zc.default or None,
            quotas=parse_quotas(zc.quotas),
            image_sizes=parse_image_sizes(zc.image_sizes),
        )
