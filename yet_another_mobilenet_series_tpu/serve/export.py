"""Checkpoint -> InferenceBundle: prune-mask surgery, EMA selection, BN fold.

The exported artifact is NOT a TrainState. Three transforms separate the two:

1. **Hard prune application.** A searched AtomNAS checkpoint carries live
   masks; serving a masked supernet would pay full-supernet FLOPs forever.
   The existing nas/rematerialize surgery (proven bit-exact against the
   masked forward) slices the dead atoms out physically.
2. **EMA selection.** Eval runs on the shadow weights (reference
   eval-on-shadow semantics); the bundle carries exactly one weight tree.
3. **BN fold.** Eval-mode BatchNorm is a per-channel affine of the adjacent
   conv's output, so it folds INTO the conv weights: ``w' = w * scale`` over
   the output-channel axis and a new bias ``b' = shift``, with
   ``(scale, shift) = ops.layers.bn_scale_shift(gamma, beta, mean, var)``.
   This is a real weight transform — the serving forward (:func:`apply_folded`)
   has no BN at all, one fewer elementwise pass over every activation, and
   the artifact has no running stats to mis-handle. Parity with the
   eval-mode BN forward is float32-rounding only (the fold re-associates a
   per-channel multiply into the conv accumulation): |logit delta| stays
   well under 1e-4 for f32 compute (pinned by tests/test_serve.py).

On disk a bundle is a directory::

    bundle/
      spec.json     network_to_dict(net, inference=True)  (schema v2)
      weights.npz   folded params, tree paths joined with '/'
      meta.json     provenance: source step, ema, prune report, and — for
                    an int8 export — the "quant" block (scheme, scales
                    accounting, calibration ranges, measured top-1
                    agreement; serve/quant.py)

``inference: true`` in the spec marks the weights as folded: the training
loader must never resume from a bundle (models/serialize.spec_is_inference).
An int8 bundle (``serve.quant.weights="int8"``) stores each quantized
conv/dense pair as ``w_q`` (int8) + ``w_scale`` (f32 per output channel) +
the f32 bias — npz round-trips the dtypes — and :func:`apply_folded`
dequantizes them in-program, so the loaded artifact and the device-resident
tree stay ~4x smaller than the f32 fold.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..models.serialize import network_from_dict, network_to_dict, spec_is_inference
from ..models.specs import Network
from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..ops.activations import get_activation
from ..ops.blocks import SqueezeExcite
from ..ops.layers import Conv2D, bn_scale_shift, global_avg_pool


# ---------------------------------------------------------------------------
# tree <-> flat npz
# ---------------------------------------------------------------------------


def flatten_tree(tree: dict, prefix: str = "") -> dict[str, np.ndarray]:
    """Nested dict-of-arrays -> {'a/b/c': array}. '/' never appears in this
    codebase's param keys (block indices are plain digits), so the join is
    unambiguous."""
    out: dict[str, np.ndarray] = {}
    for k, v in tree.items():
        if "/" in k:
            raise ValueError(f"param key {k!r} contains '/'")
        path = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(flatten_tree(v, path))
        else:
            out[path] = np.asarray(v)
    return out


def unflatten_tree(flat: dict[str, np.ndarray]) -> dict:
    out: dict = {}
    for path, v in flat.items():
        parts = path.split("/")
        cur = out
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return out


# ---------------------------------------------------------------------------
# the fold
# ---------------------------------------------------------------------------


def _fold_conv(conv_p: dict, bn_p: dict, bn_s: dict, eps: float) -> dict:
    """conv -> BN(eval) collapses to conv' with bias: the BN affine is
    per-OUTPUT-channel, and output channels are the last axis of every HWIO
    kernel (dense, grouped, and depthwise alike)."""
    scale, shift = bn_scale_shift(bn_p["gamma"], bn_p["beta"], bn_s["mean"], bn_s["var"], eps)
    return {"w": np.asarray(conv_p["w"]) * np.asarray(scale), "b": np.asarray(shift)}


def fold_network(net: Network, params: dict, state: dict) -> dict:
    """Folded serving params: every (conv, BN) pair becomes {'w','b'}; BN
    subtrees disappear; SE / dense layers pass through unchanged. The dw
    branches share one concatenated dw_bn, so each branch folds its slice of
    the (scale, shift) vectors."""
    params = jax.device_get(params)
    state = jax.device_get(state)
    out: dict[str, Any] = {}
    out["stem"] = _fold_conv(params["stem"]["conv"], params["stem"]["bn"], state["stem"]["bn"], net.stem.bn_eps)
    blocks: dict[str, Any] = {}
    for i, blk in enumerate(net.blocks):
        k = str(i)
        pb, sb = params["blocks"][k], state["blocks"][k]
        fb: dict[str, Any] = {}
        if blk.has_expand:
            fb["expand"] = _fold_conv(pb["expand"], pb["expand_bn"], sb["expand_bn"], blk.bn_eps)
        dw_scale, dw_shift = bn_scale_shift(
            pb["dw_bn"]["gamma"], pb["dw_bn"]["beta"], sb["dw_bn"]["mean"], sb["dw_bn"]["var"], blk.bn_eps
        )
        dw_scale, dw_shift = np.asarray(dw_scale), np.asarray(dw_shift)
        for bi, _kz, g, off in blk._branches():
            key = f"dw{bi}_k{_kz}"
            fb[key] = {
                "w": np.asarray(pb[key]["w"]) * dw_scale[off : off + g],
                "b": dw_shift[off : off + g],
            }
        if blk.se_channels:
            fb["se"] = pb["se"]
        fb["project"] = _fold_conv(pb["project"], pb["project_bn"], sb["project_bn"], blk.bn_eps)
        blocks[k] = fb
    out["blocks"] = blocks
    if net.head is not None:
        out["head"] = _fold_conv(params["head"]["conv"], params["head"]["bn"], state["head"]["bn"], net.head.bn_eps)
    if net.feature is not None:
        out["feature"] = params["feature"]
    out["classifier"] = params["classifier"]
    return jax.tree.map(lambda a: np.asarray(a, np.float32), out)


# ---------------------------------------------------------------------------
# the folded forward (what the engine compiles)
# ---------------------------------------------------------------------------


def _dense_params(p):
    """Folded dense params with int8 weights dequantized in-program (see
    :func:`_weight`); f32 params pass through untouched."""
    if "w_q" in p:
        return {**{k: v for k, v in p.items() if k not in ("w_q", "w_scale")},
                "w": _weight(p)}
    return p


def _weight(p):
    """The f32 weight of a folded conv/dense param dict. An int8-quantized
    pair ({'w_q', 'w_scale'}, serve/quant.py) dequantizes IN-PROGRAM —
    ``w_q.astype(f32) * w_scale`` — so the device-resident tree stays int8
    (~4x less HBM) and only the compute reads full width."""
    if "w_q" in p:
        return p["w_q"].astype(jnp.float32) * p["w_scale"]
    return p["w"]


def apply_folded(net: Network, params: dict, x, *, compute_dtype=jnp.float32, collect=None):
    """Inference forward over folded params: conv(+bias) -> act, no BN, no
    dropout, no masks (pruning was applied physically at export). Mirrors
    Network.apply's eval path structurally; the spec tree is the same
    Network — only the param tree shape differs. int8-quantized weight pairs
    (``w_q``/``w_scale``, serve/quant.py) dequantize in-program.

    ``collect`` (a dict, optional) receives per-stage activation (min, max)
    pairs — the int8 export's calibration instrument. Pass it only on EAGER
    calls (export-time calibration): under jit the collected values would be
    tracers."""

    def observe(name, h):
        if collect is not None:
            collect[name] = (jnp.min(h), jnp.max(h))
        return h

    def conv_bias_act(spec: Conv2D, p, h, act_name):
        h = spec.apply({"w": _weight(p)}, h, compute_dtype=compute_dtype)
        h = h + p["b"].astype(h.dtype)
        return get_activation(act_name)(h)

    h = x.astype(compute_dtype)
    h = observe("stem", conv_bias_act(net.stem.conv, params["stem"], h, net.stem.active_fn))
    for i, blk in enumerate(net.blocks):
        pb = params["blocks"][str(i)]
        act = get_activation(blk.active_fn)
        hin = h
        if blk.has_expand:
            h = conv_bias_act(
                Conv2D(blk.in_channels, blk.expanded_channels, 1), pb["expand"], h, blk.active_fn
            )
        branches = []
        for bi, kz, g, _off in blk._branches():
            sl = h[..., _off : _off + g]
            p = pb[f"dw{bi}_k{kz}"]
            y = Conv2D(g, g, kz, blk.stride, groups=g).apply({"w": _weight(p)}, sl, compute_dtype=compute_dtype)
            branches.append(y + p["b"].astype(y.dtype))
        h = branches[0] if len(branches) == 1 else jnp.concatenate(branches, axis=-1)
        h = act(h)
        if blk.se_channels:
            h = SqueezeExcite(blk.expanded_channels, blk.se_channels, blk.se_inner_act, blk.se_gate_fn).apply(
                pb["se"], h, compute_dtype=compute_dtype
            )
        h = conv_bias_act(Conv2D(blk.expanded_channels, blk.out_channels, 1), pb["project"], h, blk.project_act)
        if blk.has_residual:
            h = h + hin.astype(h.dtype)
        h = observe(f"block{i}", h)
    if net.head is not None:
        h = observe("head", conv_bias_act(net.head.conv, params["head"], h, net.head.active_fn))
    h = global_avg_pool(h)
    if net.feature is not None:
        h = net.feature.apply(_dense_params(params["feature"]), h, compute_dtype=compute_dtype)
        h = get_activation(net.feature_act)(h)
    return observe(
        "logits",
        net.classifier.apply(_dense_params(params["classifier"]), h.astype(jnp.float32)),
    )


# ---------------------------------------------------------------------------
# bundle I/O
# ---------------------------------------------------------------------------


class BundleDigestMismatch(ValueError):
    """The bundle's on-disk content no longer matches the digest stamped in
    ``meta.json`` at export: the artifact was corrupted or hand-edited.
    Loading refuses rather than serving silently-wrong weights — the same
    identity the fleet lease advertises per model name, so a name whose
    digest differs across replicas is caught at registration
    (serve/router.py), not by users seeing model-dependent answers."""


def bundle_digest(spec: dict, flat_params: dict[str, np.ndarray]) -> str:
    """Deterministic content digest of a bundle: the canonicalized spec JSON
    plus every weight's path/dtype/shape/bytes, in sorted path order. Stamped
    into ``meta.json`` at export, re-derived and verified at load, and
    advertised per model name on the fleet lease — two replicas claiming the
    same model name with different digests is the mixed-version foot-gun the
    router refuses at registration."""
    h = hashlib.sha256()
    h.update(json.dumps(spec, sort_keys=True).encode())
    for path in sorted(flat_params):
        a = np.ascontiguousarray(flat_params[path])
        h.update(path.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


@dataclass(frozen=True)
class InferenceBundle:
    """A loaded serving artifact: the (pruned) Network spec + folded params.
    ``params`` may carry int8-quantized weight pairs (``w_q``/``w_scale``,
    serve/quant.py) when the bundle was exported with
    ``serve.quant.weights="int8"`` — :func:`apply_folded` dequantizes them
    in-program, so the engine needs no special handling."""

    net: Network
    params: dict
    meta: dict[str, Any]

    @property
    def quant(self) -> dict | None:
        """The int8 export's provenance block (scheme, calibration ranges,
        measured top-1 agreement) — None for an f32 bundle."""
        return self.meta.get("quant")

    @property
    def model_name(self) -> str | None:
        """The zoo identity stamped at export (``export_bundle(...,
        model_name=)``) — None for a pre-zoo bundle."""
        return self.meta.get("model_name")

    @property
    def digest(self) -> str | None:
        """The verified content digest stamped at export (see
        :func:`bundle_digest`) — None for a pre-zoo bundle."""
        return self.meta.get("digest")


def export_bundle(
    net: Network,
    params: dict,
    state: dict,
    out_dir: str,
    *,
    masks: dict | None = None,
    extra_meta: dict[str, Any] | None = None,
    quant_weights: str = "float32",
    calib_images: np.ndarray | None = None,
    int8_top1_min: float = 0.98,
    model_name: str | None = None,
) -> str:
    """Write an InferenceBundle directory. ``masks`` (a live AtomNAS mask
    dict) are hard-applied via nas/rematerialize first; pass the EMA trees as
    (params, state) to export the shadow weights.

    ``model_name`` stamps the bundle's zoo identity into ``meta.json``,
    alongside a content digest (:func:`bundle_digest`) that
    :func:`load_bundle` verifies and the fleet lease advertises — the
    tamper/mixed-version guard.

    ``quant_weights="int8"`` additionally runs the gated post-training
    quantization pass (serve/quant.py): per-output-channel symmetric int8
    weights, top-1 agreement vs the f32 fold measured on ``calib_images``
    (required in this mode) and refused below ``int8_top1_min``; scales and
    calibration provenance land in ``meta.json["quant"]`` and round-trip
    through :func:`load_bundle`."""
    from .quant import WEIGHT_DTYPES, calibrate_and_quantize

    if quant_weights not in WEIGHT_DTYPES:
        raise ValueError(f"quant_weights must be one of {WEIGHT_DTYPES}, got {quant_weights!r}")
    with obs_trace.get_tracer().span("serve/export", "serve"):
        meta: dict[str, Any] = dict(extra_meta or {})
        if masks:
            np_masks = {k: np.asarray(v) for k, v in masks.items()}
            if any(m.min() == 0 for m in np_masks.values()):
                from ..nas.rematerialize import rematerialize

                net, params, state, _, _, report = rematerialize(
                    net, jax.device_get(params), jax.device_get(state), np_masks
                )
                meta["prune"] = {
                    "atoms_before": report.atoms_before,
                    "atoms_after": report.atoms_after,
                    "dropped_blocks": report.dropped_blocks,
                }
        folded = fold_network(net, params, state)
        if quant_weights == "int8":
            if calib_images is None:
                raise ValueError("int8 export needs a calibration batch (calib_images)")
            folded, meta["quant"] = calibrate_and_quantize(
                net, folded, calib_images, top1_min=int8_top1_min
            )
            get_registry().counter("serve.int8_exports").inc()
        os.makedirs(out_dir, exist_ok=True)
        spec_dict = network_to_dict(net, inference=True)
        flat = flatten_tree(folded)
        if model_name is not None:
            meta["model_name"] = model_name
        meta["digest"] = bundle_digest(spec_dict, flat)
        with open(os.path.join(out_dir, "spec.json"), "w") as f:
            json.dump(spec_dict, f, indent=1)
        np.savez(os.path.join(out_dir, "weights.npz"), **flat)
        with open(os.path.join(out_dir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1, default=str)
    get_registry().counter("serve.exports").inc()
    return out_dir


def export_checkpoint(
    ckpt_dir: str,
    out_dir: str,
    *,
    use_ema: bool = True,
    step: int | None = None,
    quant_weights: str = "float32",
    calib_images: np.ndarray | None = None,
    int8_top1_min: float = 0.98,
) -> str:
    """Orbax checkpoint directory -> bundle: two-phase restore (spec first,
    pruned-shape ordering), EMA selection, then :func:`export_bundle` (which
    the int8 quantization knobs pass straight through to)."""
    from ..ckpt.manager import CheckpointManager

    mgr = CheckpointManager(ckpt_dir, barrier_prefix="serve_export")
    try:
        spec = mgr.restore_spec(step)
        if spec is None:
            raise FileNotFoundError(f"no checkpoint found under {ckpt_dir!r}")
        found_step, net, extra = spec
        # as-saved restore (no abstract target): export only reads weight
        # trees and needs no optimizer skeleton at the pruned shape
        tree = mgr.restore_tree(found_step)
    finally:
        mgr.close()
    ema_ok = use_ema and tree.get("ema_params") is not None
    params = tree["ema_params"] if ema_ok else tree["params"]
    state = tree["ema_state"] if ema_ok else tree["state"]
    return export_bundle(
        net, params, state, out_dir,
        masks=tree.get("masks") or None,
        extra_meta={"source": ckpt_dir, "step": int(np.asarray(tree["step"])), "ema": ema_ok,
                    "epoch": (extra or {}).get("epoch")},
        quant_weights=quant_weights, calib_images=calib_images,
        int8_top1_min=int8_top1_min,
    )


def load_bundle(bundle_dir: str) -> InferenceBundle:
    with open(os.path.join(bundle_dir, "spec.json")) as f:
        spec = json.load(f)
    if not spec_is_inference(spec):
        raise ValueError(
            f"{bundle_dir!r} is not an inference bundle (spec lacks the folded-BN "
            "marker); export it with serve.export first"
        )
    net = network_from_dict(spec)
    with np.load(os.path.join(bundle_dir, "weights.npz")) as z:
        params = unflatten_tree({k: z[k] for k in z.files})
    meta_path = os.path.join(bundle_dir, "meta.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    # identity verification: a digest-stamped bundle (every zoo export) is
    # re-derived from what was actually read off disk; a mismatch refuses to
    # load rather than serving corrupted/hand-edited weights. Pre-zoo
    # bundles (no digest in meta) load as before.
    stamped = meta.get("digest")
    if stamped is not None:
        actual = bundle_digest(spec, {k: np.asarray(v) for k, v in flatten_tree(params).items()})
        if actual != stamped:
            raise BundleDigestMismatch(
                f"bundle {bundle_dir!r} content digest {actual} != stamped {stamped}; "
                "the artifact was modified after export — re-export it"
            )
    return InferenceBundle(net=net, params=params, meta=meta)
