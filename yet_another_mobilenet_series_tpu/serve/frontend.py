"""Loopback HTTP front door: the network edge in front of the batcher.

Pure stdlib (``http.server``), one import away from nothing — the container
bakes no RPC framework, and the point of this layer is failure BEHAVIOR,
not protocol sophistication: every request resolves to a result, a typed
rejection, or a timeout, with the resilience semantics (admission control,
retry, breaker — serve/admission.py) mapped onto HTTP status codes a load
balancer already understands.

Endpoints:

``POST /predict``
    One image per request. Body is either JSON ``{"image": [[[...]]]}``
    (H, W, 3 nested lists) or raw bytes
    (``Content-Type: application/octet-stream``) with an ``X-Shape: H,W,C``
    header and an optional ``X-Dtype`` header — ``f4`` (little-endian
    float32, the default: pre-header clients keep working) or ``u8`` (raw
    uint8 pixels, the quantized wire: 4x fewer bytes per request, riding
    router->replica across the fleet when ``serve.quant.wire="uint8"``). Per-request QoS rides in headers — ``X-Priority:
    interactive|batch|best_effort`` and ``X-Deadline-Ms: <float>`` — and is
    propagated into the admission controller and batcher verbatim.
    Responses: ``200`` ``{"logits": [...], "priority": cls}``;
    ``400`` malformed body/headers; ``429`` rejected at arrival (class
    quota, queue full, or deadline-unmeetable — body carries which);
    ``503`` breaker open (with ``Retry-After``) or shutdown drain;
    ``504`` deadline exceeded / server-side timeout; ``500`` engine error
    after retries. Every error body is ``{"error": <type>, "message": ...}``.

``GET /healthz``
    The admission controller's state snapshot — breaker state (+ the
    ``serve.breaker_state`` gauge value), per-class queue occupancy vs
    quota, EWMA/predicted wait, in-flight window occupancy. Status ``200``
    while the breaker is closed or half-open, ``503`` while open — a load
    balancer can drain a sick replica from rotation without parsing JSON.

``POST /register`` / ``POST /deregister``
    TTL-leased membership, served only when the admission object speaks it
    (the fleet Router does; a plain replica answers 404). A replica POSTs
    ``{"host", "port", "ttl_s", "replica_id"}`` to join the fleet and
    heartbeats the same body to renew; a lease that expires unrenewed
    removes the backend (serve/router.py). This is the multi-host
    registration path: remote replicas join a router that never spawned
    them.

``POST /profile/start`` / ``POST /profile/stop``
    HTTP-triggered ``jax.profiler`` capture of LIVE serving traffic
    (obs/device.py :class:`~..obs.device.ProfilerCapture`): start opens an
    xplane trace window under the configured trace dir, stop closes it and
    returns the dir + captured seconds for scripts/trace_ops.py aggregation.
    Single-flight: a second start (or a stop with no capture open) is
    ``409``; a window still open at SIGTERM is closed by the drain path
    (cli/serve.py), never leaked. ``404`` when no profiler is configured.

The server is a ``ThreadingHTTPServer`` bound to loopback by default
(``cli/serve.py --listen``); its accept loop runs on a guarded daemon
thread (YAMT011). ``stop()`` shuts the accept loop down and returns — the
batcher drain (bounded by ``serve.drain_timeout_s``) is the caller's next
line, so SIGTERM = stop accepting, then drain in-flight work.
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..utils.logging import emit
from .admission import (
    BreakerOpen,
    BrownoutShed,
    DeadlineUnmeetable,
    BREAKER_OPEN,
    UnknownModel,
)
from .batcher import DeadlineExceeded, DrainTimeout, QueueFull
from .client import WIRE_DTYPES, ClientHTTPError, ClientTimeout
from .context import RequestContext
from .router import ModelDigestConflict, NoHealthyReplicas, NoReplicaForModel

# this process's birth time: the replica-identity field a router compares to
# detect a RESTARTED replica behind an unchanged address (same host:port,
# new process) — pid alone can recycle. Wall clock BY DESIGN (an identity
# timestamp routers compare across hosts, never differenced into a duration
# — the YAMT017 hazard is subtraction, not the reading).
_PROC_START_UNIX = time.time()

# exception type -> (HTTP status, wire error tag, overload-shaped?); anything
# else is a 500. Subtype rows precede their base (isinstance scan):
# UnknownModel is a client-side naming error (400, never overload-shaped),
# NoReplicaForModel a placement gap distinct from a dead fleet. The final
# column marks "alive but saturated — come back": those verdicts carry a
# Retry-After header (RFC 9110), which is ALSO the router's backpressure
# discriminator (a Retry-After-bearing 503 never scores toward ejection).
# "draining" and "no_healthy_replicas" mean "stop sending here" — no hint.
_ERROR_MAP = [
    (BreakerOpen, 503, "breaker_open", True),
    (BrownoutShed, 503, "brownout", True),
    (DeadlineUnmeetable, 429, "deadline_unmeetable", True),
    (UnknownModel, 400, "unknown_model", False),
    (QueueFull, 429, "queue_full", True),  # covers ClassQueueFull / ModelQueueFull too
    (DeadlineExceeded, 504, "deadline_exceeded", False),
    (DrainTimeout, 503, "draining", False),
    (NoReplicaForModel, 503, "no_replica_for_model", False),
    (NoHealthyReplicas, 503, "no_healthy_replicas", False),
    (ClientTimeout, 504, "timeout", False),
]

# derived, not hand-kept: the one source of truth for overload-shaped tags
# is the _ERROR_MAP row itself
_RETRY_AFTER_TAGS = frozenset(
    tag for _typ, _status, tag, retry_after in _ERROR_MAP if retry_after
)


def _classify(exc: Exception) -> tuple[int, str]:
    # a replica's typed verdict crossing the router passes through verbatim
    # (fleet-behind-the-frontend is indistinguishable from one replica)
    if isinstance(exc, ClientHTTPError):
        return exc.status, exc.tag
    for typ, status, tag, _retry_after in _ERROR_MAP:
        if isinstance(exc, typ):
            return status, tag
    return 500, "engine_error"


def _retry_after_s(exc: Exception, status: int, tag: str, default_s: float) -> float | None:
    """The Retry-After seconds for one error response, or None for no
    header: an exception-carried hint wins (BrownoutShed's own bound, a
    replica's header passing through the router verbatim), then the
    frontend default for every overload-shaped 429/503 tag."""
    carried = getattr(exc, "retry_after_s", None)  # BrownoutShed
    if carried is None:
        carried = getattr(exc, "retry_after", None)  # ClientHTTPError pass-through
    if carried is not None:
        return float(carried)
    if status in (429, 503) and tag in _RETRY_AFTER_TAGS:
        return default_s
    return None


def write_listen_addr(log_dir: str, addr: dict) -> str:
    """Publish the bound address ATOMICALLY as ``<log_dir>/listen_addr.json``:
    write a temp file, then rename. A polling supervisor (cli/fleet.py) reads
    either nothing or whole JSON — never a partial document."""
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir, "listen_addr.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(addr, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic on POSIX: readers see old-or-new, whole
    return path


class _Handler(BaseHTTPRequestHandler):
    """Request handler; the owning :class:`Frontend` is injected as a class
    attribute by :meth:`Frontend.start` (stdlib handler classes are
    instantiated per request by the server, so state rides on the class)."""

    frontend: "Frontend" = None  # type: ignore[assignment]
    protocol_version = "HTTP/1.1"

    # -- plumbing -----------------------------------------------------------

    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        # per-request stderr lines would fork the logging path (YAMT007
        # discipline); request accounting lives in the obs registry instead
        get_registry().counter("serve.http_requests").inc()

    def _send_json(self, status: int, payload: dict, headers: dict | None = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_error_json(self, status: int, tag: str, message: str, headers: dict | None = None) -> None:
        get_registry().counter("serve.http_errors").inc()
        self._send_json(status, {"error": tag, "message": message}, headers)

    def _send_typed_error(self, exc: Exception, rid_hdr: dict) -> None:
        """Map one typed failure to its wire verdict, attaching Retry-After
        to every overload-shaped 429/503 (exception-carried hints — a
        brownout shed's own bound, a replica's header crossing the router —
        pass through verbatim)."""
        status, tag = _classify(exc)
        headers = dict(rid_hdr)
        retry_after = _retry_after_s(exc, status, tag, self.frontend.retry_after_s)
        if retry_after is not None:
            headers["Retry-After"] = f"{max(retry_after, 0.0):.0f}"
        body = {"error": tag, "message": str(exc)}
        # model-routing verdicts carry the served-model list structurally, so
        # a client can correct its X-Model without parsing prose
        served = getattr(exc, "served", None)
        if served is not None:
            body["served"] = sorted(served)
        get_registry().counter("serve.http_errors").inc()
        self._send_json(status, body, headers)

    # -- GET /healthz, /metrics, /varz --------------------------------------

    def do_GET(self):  # noqa: N802 — stdlib method name
        if self.path == "/healthz":
            self._get_healthz()
        elif self.path == "/metrics":
            self._get_metrics()
        elif self.path == "/varz":
            self._get_varz()
        else:
            self._send_error_json(404, "not_found", f"no route {self.path}")

    def _get_healthz(self) -> None:
        fe = self.frontend
        state = fe.admission.state()
        state["inflight"] = int(get_registry().gauge("serve.inflight").value)
        state["draining"] = fe._draining
        # the degradation ladder's position (0 = healthy): rides health so a
        # poller/load balancer sees HOW degraded, not just up-or-down
        state["brownout_level"] = int(get_registry().gauge("serve.brownout_level").value)
        # replica identity: lets a router/obs_report attribute this health
        # to a specific process and detect a restart behind the same address
        state["replica"] = fe.identity()
        status = 503 if state["breaker_state"] == BREAKER_OPEN else 200
        state["ok"] = status == 200 and not fe._draining
        self._send_json(status, state)

    def _get_metrics(self) -> None:
        """Prometheus text exposition of the whole obs registry — the scrape
        surface a multi-replica deployment's collector reads. Histograms emit
        cumulative bucket + quantile lines (obs/registry.py), so
        ``serve_latency_seconds{class="interactive",quantile="0.99"}`` is
        p99 straight off the replica."""
        text = get_registry().render_prometheus()
        if self.frontend.federation is not None:
            # the router frontend is ALSO the fleet's scrape surface:
            # federated families (replica-labeled histograms, fleet gauges,
            # every replica's build_info) ride the same exposition
            text += self.frontend.federation.render_prometheus()
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _get_varz(self) -> None:
        """JSON twin of /metrics for humans and tests: the full registry
        snapshot (histograms expanded with min/max/p50/p95/p99) plus the
        admission state, the oldest in-flight request, build identity, and
        the per-executable compile/cost table (obs/device.py)."""
        from ..obs.device import compile_report

        fe = self.frontend
        doc = {
            "metrics": get_registry().snapshot(),
            "admission": fe.admission.state(),
            "draining": fe._draining,
            "replica": fe.identity(),
            "build_info": get_registry().build_info,
            "executables": compile_report(),
            # raw bucket counts per histogram: the federation scrape's input
            # — fixed log-spaced bounds make cross-replica count summation a
            # LOSSLESS merge (obs/fleet.py)
            "histograms": get_registry().histograms_state(),
        }
        if fe.federation is not None:
            doc["fleet"] = fe.federation.snapshot()
        self._send_json(200, doc)

    # -- POST /predict ------------------------------------------------------

    def _parse_image(self) -> np.ndarray:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            raise ValueError("empty body")
        body = self.rfile.read(length)
        ctype = (self.headers.get("Content-Type") or "application/json").split(";")[0].strip()
        if ctype == "application/octet-stream":
            shape_hdr = self.headers.get("X-Shape", "")
            try:
                shape = tuple(int(s) for s in shape_hdr.split(","))
            except ValueError:
                raise ValueError(f"X-Shape must be 'H,W,C' integers, got {shape_hdr!r}") from None
            # X-Dtype picks the wire encoding; absent = the historical
            # little-endian float32 contract. "u8" carries RAW pixels — the
            # quantized wire's 4x byte drop crossing the fleet intact
            dtype_code = (self.headers.get("X-Dtype") or "f4").strip().lower()
            if dtype_code not in WIRE_DTYPES:
                raise ValueError(
                    f"X-Dtype must be one of {sorted(WIRE_DTYPES)}, got {dtype_code!r}")
            image = np.frombuffer(body, dtype=WIRE_DTYPES[dtype_code])
            if len(shape) != 3 or int(np.prod(shape)) != image.size:
                raise ValueError(
                    f"X-Shape {shape} does not match {image.size} {dtype_code} values")
            image = image.reshape(shape)
        else:
            try:
                doc = json.loads(body)
                image = np.asarray(doc["image"], np.float32)
            except (json.JSONDecodeError, KeyError, TypeError) as e:
                raise ValueError(f"body must be JSON with an 'image' key: {e}") from None
        if image.ndim != 3:
            raise ValueError(f"image must be (H, W, C), got shape {tuple(image.shape)}")
        return image

    def _post_profile(self) -> None:
        """Start/stop the serving profiler capture (obs/device.py). State
        errors (already running / nothing to stop) are 409 so an operator's
        double-tap is loud but harmless; jax.profiler failures are 500."""
        fe = self.frontend
        if fe.profiler is None:
            self._send_error_json(404, "not_found", "no profiler configured (set a log dir)")
            return
        try:
            out = fe.profiler.start() if self.path == "/profile/start" else fe.profiler.stop()
        except RuntimeError as e:
            self._send_error_json(409, "profiler_state", str(e))
            return
        except Exception as e:  # noqa: BLE001 — a torn capture surfaces typed
            self._send_error_json(500, "profiler_error", f"{type(e).__name__}: {e}")
            return
        self._send_json(200, {"ok": True, **out})

    def _post_membership(self) -> None:
        """POST /register|/deregister: the TTL-lease membership endpoints,
        live only when the admission object speaks them (the fleet Router).
        A replica heartbeats /register to stay in the fleet; /deregister is
        the clean-drain fast path."""
        fe = self.frontend
        target = getattr(fe.admission, "register", None)
        if target is None:
            self._send_error_json(404, "not_found",
                                  "membership endpoints need a fleet router")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length)) if length > 0 else {}
            host, port = doc["host"], int(doc["port"])
        except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
            self._send_error_json(400, "bad_request",
                                  f"body must be JSON with host/port: {e}")
            return
        try:
            if self.path == "/register":
                kw = dict(ttl_s=doc.get("ttl_s"),
                          replica_id=str(doc.get("replica_id", "")))
                if doc.get("models") is not None:
                    # only zoo replicas advertise; keeps pre-zoo register()
                    # implementations (and test doubles) working unchanged
                    kw["models"] = doc["models"]
                out = fe.admission.register(host, port, **kw)
            else:
                out = fe.admission.deregister(host, port)
        except ModelDigestConflict as e:
            # split-brain artifact identity: same model name, different
            # content digest across live replicas — the late joiner is
            # refused with a conflict verdict, not folded into the lottery
            self._send_error_json(409, "digest_conflict", str(e))
            return
        except ValueError as e:
            self._send_error_json(400, "bad_request", str(e))
            return
        self._send_json(200, out)

    def do_POST(self):  # noqa: N802 — stdlib method name
        if self.path in ("/profile/start", "/profile/stop"):
            self._post_profile()
            return
        if self.path in ("/register", "/deregister"):
            self._post_membership()
            return
        if self.path != "/predict":
            self._send_error_json(404, "not_found", f"no route {self.path}")
            return
        fe = self.frontend
        try:
            image = self._parse_image()
            deadline_hdr = self.headers.get("X-Deadline-Ms")
            deadline_ms = float(deadline_hdr) if deadline_hdr else None
            priority = self.headers.get("X-Priority") or None
            # X-Model names the zoo tenant; absent = the default model (a
            # pre-zoo client keeps working). It rides the RequestContext
            # into admission (validation + per-model quota), the batcher's
            # (model, shape) grouping, and the router's model-aware pick
            model = (self.headers.get("X-Model") or "").strip() or None
        except ValueError as e:
            self._send_error_json(400, "bad_request", str(e))
            return
        # request identity: a process-monotonic id minted HERE, echoed on
        # every response as X-Request-Id (a client-supplied value is echoed
        # back verbatim as the wire id; the internal id stays monotonic —
        # trace correlation needs process-unique ids) and threaded through
        # admission -> batcher -> engine as the trace correlation key
        ctx = RequestContext.mint(
            priority or fe.admission._default_class, deadline_ms,
            client_tag=self.headers.get("X-Request-Id") or None,
            # the router's per-leg fleet trace identity (context.py): replica
            # trace events carry the ROUTER-issued request id, and
            # link_parent below lands the router->replica flow arrow
            trace_parent=self.headers.get("X-Trace-Parent") or None,
            model=model,
        )
        rid_hdr = {"X-Request-Id": ctx.wire_id}
        try:
            with obs_trace.get_tracer().span("serve/submit", "serve", rid=ctx.rid,
                                             **ctx._targs()):
                ctx.link_parent()
                fut = fe.admission.submit(
                    image, priority=priority, deadline_ms=deadline_ms, ctx=ctx
                )
        except ValueError as e:  # unknown priority class
            self._send_error_json(400, "bad_request", str(e), rid_hdr)
            return
        except Exception as e:  # noqa: BLE001 — typed arrival rejections
            self._send_typed_error(e, rid_hdr)
            return
        # the handler thread is this request's only waiter: a deadline
        # extends the server bound (the admission/batcher layers resolve the
        # future well before this backstop unless something is truly wedged)
        timeout_s = fe.request_timeout_s + (deadline_ms or 0.0) / 1e3
        try:
            logits = fut.result(timeout=timeout_s)
        except (TimeoutError, FutureTimeout):
            self._send_error_json(504, "timeout", f"no result within {timeout_s:.1f}s", rid_hdr)
            return
        except Exception as e:  # noqa: BLE001 — typed shed/failure outcomes
            self._send_typed_error(e, rid_hdr)
            return
        self._send_json(
            200,
            {"logits": np.asarray(logits, np.float64).tolist(),
             "priority": priority or fe.admission._default_class,
             "request_id": ctx.wire_id},
            rid_hdr,
        )


class Frontend:
    """Owns the HTTP server + accept-loop thread around an
    :class:`~.admission.AdmissionController`."""

    def __init__(
        self,
        admission,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        request_timeout_s: float = 60.0,
        retry_after_s: float = 1.0,
        profiler=None,
        replica_id: str = "",
        federation=None,
    ):
        self.admission = admission
        # obs/device.py ProfilerCapture (or None): POST /profile/start|stop
        self.profiler = profiler
        # obs/fleet.py FleetFederation (or None): set on the ROUTER's
        # frontend, it extends /metrics with replica-labeled federated
        # families and /varz with the fleet snapshot
        self.federation = federation
        self._host = host
        self._port = port
        self.request_timeout_s = request_timeout_s
        self.retry_after_s = retry_after_s
        # stable name a supervisor assigns (serve.listen.replica_id); ports
        # are ephemeral and pids recycle, so health/restart attribution
        # needs an identity that survives both
        self.replica_id = replica_id or f"pid-{os.getpid()}"
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._draining = False

    def identity(self) -> dict:
        """The replica identity block on /healthz and /varz: who is serving
        behind this address, and since when."""
        return {
            "replica_id": self.replica_id,
            "pid": os.getpid(),
            "start_unix": _PROC_START_UNIX,
            "git_sha": get_registry().build_info.get("git_sha", ""),
        }

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("frontend not started")
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def start(self) -> "Frontend":
        if self._server is not None:
            raise RuntimeError("frontend already started")
        handler = type("_BoundHandler", (_Handler,), {"frontend": self})
        self._server = ThreadingHTTPServer((self._host, self._port), handler)
        self._server.daemon_threads = True  # handler threads never block exit
        self._thread = threading.Thread(target=self._serve, name="serve-http", daemon=True)
        self._thread.start()
        return self

    def _serve(self) -> None:
        try:
            obs_trace.get_tracer().register_thread()  # "serve-http" Perfetto row
            self._server.serve_forever(poll_interval=0.1)
        except Exception as e:  # noqa: BLE001 — YAMT011: never die silently
            get_registry().counter("serve.thread_crashes").inc()
            emit(f"[serve] http accept loop crashed: {type(e).__name__}: {e}")

    def stop(self) -> None:
        """Stop accepting; in-flight handler threads finish their responses.
        The caller drains the batcher next (bounded by drain_timeout_s)."""
        if self._server is None:
            return
        self._draining = True
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        self._server = None  # yamt-lint: disable=YAMT019 — teardown: shutdown() has returned serve_forever and the accept thread was joined above
        self._thread = None
