"""Socket-level network chaos: a TCP fault-injection proxy between router
and replica.

Every fault the fleet has survived so far lives INSIDE a process boundary:
serve/faults.py injects at the engine edge, cli/fleet.py kills or SIGSTOPs
whole replicas. Crossing hosts (ROADMAP item 1's remaining rung) adds the
failure class neither can produce — the NETWORK itself misbehaving — and
the tail-at-scale literature says partitions, not crashes, dominate
multi-host fleets. A blackholed replica is worse than a dead one: a dead
socket refuses instantly (connect error, retried in microseconds), a
blackholed one accepts and then says nothing, pinning every leg for the
full read timeout.

:class:`NetChaosProxy` is a stdlib-socket TCP proxy interposed between the
router and one replica frontend, so every partition shape is reproducible
on one box without root or iptables:

- ``blackhole`` — accept the TCP connection, never forward a byte in either
  direction (SYN-eats-everything): connects "succeed", then everything
  hangs. Live keep-alive pipes stall too — a partition does not spare
  established connections.
- ``reset`` — connections are torn down with an RST (SO_LINGER 0): the
  abrupt peer-death signal, distinct from a clean FIN.
- ``half_open`` — the classic half-open socket: connect succeeds, request
  bytes are consumed, reads hang forever (the peer died without FIN and
  something still ACKs — NAT boxes and dead VMs do this).
- ``drop_response`` — asymmetric loss: the request IS forwarded (the
  replica does the work), the response is dropped. The client cannot tell
  this from half_open; the replica-side books can — which is exactly why
  retries must be idempotence-aware.
- **latency / jitter** — each response chunk is delayed ``latency_ms`` plus
  a seeded uniform draw in ``[0, jitter_ms]`` (WAN RTT, not loopback).
- **throttle** — response bandwidth capped at ``bandwidth_kbps`` (kilobits
  per second), the congested-link stand-in.
- **flap** — a timed link schedule: down (blackhole) for ``flap_down_s``
  out of every ``flap_period_s``, measured from proxy start on the
  monotonic clock. The drill for ejection/readmission ping-pong.

Determinism: the per-connection fault plan is a pure function of
``(seed, connection index, settings)`` — :meth:`NetChaosProxy.plan_for` is
reproducible without running any traffic, and two proxies built with the
same seed and settings produce identical plans (pinned in
tests/test_netchaos.py). ``fault_rate`` < 1 applies the configured shape to
a seeded subset of connections (flaky-path chaos); the default 1.0 models a
link-level fault that spares nothing.

:meth:`set_fault` reconfigures the LIVE proxy (the bench's mid-round
partition onset): held blackhole/half-open connections are released —
closed, the way a healed route drops the stale conntrack state — and new
connections see the new shape immediately.

:class:`NetChaosTier` manages one proxy per replica address and is what
cli/fleet.py wires between the supervisor's membership notifications and
``Router.set_backends`` (``serve.fleet.netchaos``); ``FleetChaos``
``mode="partition"`` drives a seeded victim proxy through a timed fault
episode the same way ``mode="degrade"`` drives SIGSTOP pulses.

Everything here is stdlib sockets + threads: no jax import (supervisors
stay device-free), every socket carries an explicit timeout (the YAMT018
discipline this PR adds — the proxy that TESTS hangs must never hang
itself), every thread target is guarded (YAMT011), and all durations ride
the monotonic clock (YAMT017).
"""

from __future__ import annotations

import random
import select
import socket
import struct
import threading
import time

from ..obs.registry import get_registry

FAULT_SHAPES = ("blackhole", "reset", "half_open", "drop_response")

# pump granularity: how long a select() wait lasts before re-checking link
# state / stop, and how long a stalled (blackholed) pump sleeps per tick
_TICK_S = 0.05
# per-socket timeout: bounds a pathological recv/sendall (a wedged peer)
# without polluting the select-paced poll loop — readiness comes from
# select, so a post-select recv returns promptly
_SOCK_TIMEOUT_S = 30.0
_CHUNK = 16384


class FaultPlan:
    """One connection's materialized fault plan: the shape it experiences
    (None = clean pass-through), plus the shaping parameters and the
    per-connection jitter stream seed. A pure function of (proxy seed,
    connection index, settings) — see :meth:`NetChaosProxy.plan_for`."""

    __slots__ = ("idx", "shape", "applies", "latency_s", "jitter_s",
                 "bytes_per_s", "jitter_seed")

    def __init__(self, idx, shape, applies, latency_s, jitter_s, bytes_per_s, jitter_seed):
        self.idx = idx
        self.shape = shape if applies else None
        self.applies = applies
        self.latency_s = latency_s if applies else 0.0
        self.jitter_s = jitter_s if applies else 0.0
        self.bytes_per_s = bytes_per_s if applies else 0.0
        self.jitter_seed = jitter_seed

    def as_dict(self) -> dict:
        return {"idx": self.idx, "shape": self.shape, "applies": self.applies,
                "latency_s": self.latency_s, "jitter_s": self.jitter_s,
                "bytes_per_s": self.bytes_per_s, "jitter_seed": self.jitter_seed}


class NetChaosProxy:
    """Seeded TCP fault-injection proxy in front of one upstream address."""

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        seed: int = 0,
        fault: str | None = None,
        fault_rate: float = 1.0,
        latency_ms: float = 0.0,
        jitter_ms: float = 0.0,
        bandwidth_kbps: float = 0.0,
        flap_period_s: float = 0.0,
        flap_down_s: float = 0.0,
        connect_timeout_s: float = 2.0,
    ):
        if fault is not None and fault not in FAULT_SHAPES:
            raise ValueError(f"fault must be one of {FAULT_SHAPES} or None, got {fault!r}")
        if not 0.0 <= fault_rate <= 1.0:
            raise ValueError(f"fault_rate must be in [0, 1], got {fault_rate}")
        if flap_period_s > 0 and not 0.0 < flap_down_s < flap_period_s:
            raise ValueError("flap needs 0 < flap_down_s < flap_period_s")
        self.upstream_host = upstream_host
        self.upstream_port = int(upstream_port)
        self._host = host
        self._port = int(port)
        self._seed = int(seed)
        self._connect_timeout_s = connect_timeout_s
        self._lock = threading.Lock()
        # live-switchable settings; _gen bumps on every set_fault so held
        # (blackholed / half-open) connections release on reconfigure
        self._fault = fault
        self._fault_rate = fault_rate
        self._latency_s = latency_ms / 1e3
        self._jitter_s = jitter_ms / 1e3
        self._bytes_per_s = bandwidth_kbps * 125.0  # kilobits/s -> bytes/s
        self._flap_period_s = flap_period_s
        self._flap_down_s = flap_down_s
        self._gen = 0
        self._flap_was_down = False
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._t0 = 0.0  # monotonic flap-schedule origin, set at start()
        self._conn_idx = 0
        self._open_socks: set[socket.socket] = set()
        self._reg = get_registry()

    # -- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> int:
        if self._listener is None:
            raise RuntimeError("proxy not started")
        return self._listener.getsockname()[1]

    @property
    def addr(self) -> tuple[str, int]:
        return (self._host, self.port)

    def start(self) -> "NetChaosProxy":
        if self._listener is not None:
            raise RuntimeError("proxy already started")
        self._stop.clear()
        with self._lock:  # set_fault/_link_down access _t0 under the same lock
            self._t0 = time.monotonic()
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(_TICK_S * 4)  # bounded accept waits: stop() never hangs
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._host, self._port))
        s.listen(64)
        self._listener = s
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"netchaos-{self.upstream_port}", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
            self._accept_thread = None
        if self._listener is not None:
            self._listener.close()
            self._listener = None  # yamt-lint: disable=YAMT019 — teardown handshake: the accept loop maps the resulting OSError to "stop() is running" and exits
        with self._lock:
            socks, self._open_socks = set(self._open_socks), set()
        for c in socks:
            try:
                c.close()
            except OSError:
                pass

    # -- live reconfiguration (the mid-round partition onset) ----------------

    def set_fault(self, fault: str | None, **kw) -> None:
        """Switch the injected fault live. ``kw`` may override ``fault_rate``,
        ``latency_ms``, ``jitter_ms``, ``bandwidth_kbps``, ``flap_period_s``,
        ``flap_down_s``. Held blackhole/half-open connections are released
        (closed) — a healed route drops stale state; a new fault must not
        wait for old sockets to notice."""
        if fault is not None and fault not in FAULT_SHAPES:
            raise ValueError(f"fault must be one of {FAULT_SHAPES} or None, got {fault!r}")
        with self._lock:
            self._fault = fault
            if "fault_rate" in kw:
                self._fault_rate = float(kw["fault_rate"])
            if "latency_ms" in kw:
                self._latency_s = float(kw["latency_ms"]) / 1e3
            if "jitter_ms" in kw:
                self._jitter_s = float(kw["jitter_ms"]) / 1e3
            if "bandwidth_kbps" in kw:
                self._bytes_per_s = float(kw["bandwidth_kbps"]) * 125.0
            if "flap_period_s" in kw:
                self._flap_period_s = float(kw["flap_period_s"])
            if "flap_down_s" in kw:
                self._flap_down_s = float(kw["flap_down_s"])
            self._gen += 1
            self._t0 = time.monotonic()  # flap schedule restarts at the switch

    def clear(self) -> None:
        """Heal the link completely: fault shape, shaping, AND the flap
        schedule (a "healed" link that keeps flapping is not healed)."""
        self.set_fault(None, latency_ms=0.0, jitter_ms=0.0, bandwidth_kbps=0.0,
                       flap_period_s=0.0, flap_down_s=0.0)

    # -- the deterministic plan ----------------------------------------------

    def plan_for(self, idx: int) -> FaultPlan:
        """The fault plan connection ``idx`` experiences: a pure function of
        (seed, idx, current settings) — same seed + settings => same plan,
        with no shared RNG state, so concurrent accepts stay deterministic
        per index and tests can predict a schedule without traffic."""
        with self._lock:
            fault, rate = self._fault, self._fault_rate
            latency_s, jitter_s, bps = self._latency_s, self._jitter_s, self._bytes_per_s
        rng = random.Random((self._seed * 1_000_003) ^ (idx * 7919))
        applies = rng.random() < rate
        return FaultPlan(idx, fault, applies, latency_s, jitter_s, bps,
                         jitter_seed=rng.randrange(1 << 30))

    def _link_down(self) -> bool:
        """Flap schedule: down for flap_down_s out of every flap_period_s,
        phase measured from the monotonic start/reconfigure origin."""
        with self._lock:
            period, down = self._flap_period_s, self._flap_down_s
            if period <= 0:
                return False
            is_down = (time.monotonic() - self._t0) % period < down
            if is_down != self._flap_was_down:
                self._flap_was_down = is_down
                self._reg.counter("serve.netchaos.flap_transitions").inc()
            return is_down

    def _shape_now(self, plan: FaultPlan) -> str | None:
        """The effective fault for one connection RIGHT NOW: its plan shape
        while the settings generation holds, with the flap schedule
        overriding to blackhole during down windows."""
        if self._link_down():
            return "blackhole"
        return plan.shape

    # -- accept + pump threads ------------------------------------------------

    def _accept_loop(self) -> None:
        try:  # YAMT011: a dead accept loop is a silent total partition
            while not self._stop.is_set():
                try:
                    client, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return  # listener closed under us: stop() is running
                with self._lock:
                    idx = self._conn_idx
                    self._conn_idx += 1
                    gen = self._gen
                    self._open_socks.add(client)
                self._reg.counter("serve.netchaos.connections").inc()
                threading.Thread(
                    target=self._serve_conn_guarded, args=(idx, gen, client),
                    name=f"netchaos-conn-{idx}", daemon=True,
                ).start()
        except Exception:  # noqa: BLE001 — contain, count (YAMT011)
            self._reg.counter("serve.thread_crashes").inc()

    def _serve_conn_guarded(self, idx: int, gen: int, client: socket.socket) -> None:
        try:  # YAMT011
            self._serve_conn(idx, gen, client)
        except Exception:  # noqa: BLE001 — a torn pump fails one conn, not the proxy
            self._reg.counter("serve.thread_crashes").inc()
        finally:
            self._forget(client)
            try:
                client.close()
            except OSError:
                pass

    def _forget(self, sock: socket.socket) -> None:
        with self._lock:
            self._open_socks.discard(sock)

    def _gen_moved(self, gen: int) -> bool:
        with self._lock:
            return self._gen != gen

    @staticmethod
    def _rst_close(sock: socket.socket) -> None:
        """Close with an RST instead of a FIN: SO_LINGER (on, 0) makes the
        kernel abort the connection — the peer sees ECONNRESET."""
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _hold(self, idx: int, gen: int, client: socket.socket, shape: str) -> None:
        """Blackhole / half-open hold: the connection goes nowhere. Blackhole
        never reads (send buffers fill like a routed-to-nowhere link);
        half-open consumes request bytes and answers nothing. Released when
        the settings generation moves (fault cleared) or the proxy stops."""
        if shape == "blackhole":
            self._reg.counter("serve.netchaos.blackholed").inc()
        else:
            self._reg.counter("serve.netchaos.half_open").inc()
        client.settimeout(_SOCK_TIMEOUT_S)
        while not self._stop.is_set() and not self._gen_moved(gen):
            if shape == "half_open":
                try:
                    readable, _, _ = select.select([client], [], [], _TICK_S)
                    if not readable:
                        continue
                    data = client.recv(_CHUNK)
                    if not data:
                        return  # the client gave up: clean half-close
                except OSError:
                    return
            else:
                self._stop.wait(_TICK_S)
        # released: a healed link drops the stale state — the client's next
        # use of this socket fails fast and retries on a fresh connection

    def _serve_conn(self, idx: int, gen: int, client: socket.socket) -> None:
        plan = self.plan_for(idx)
        shape = self._shape_now(plan)
        if shape == "reset":
            self._reg.counter("serve.netchaos.resets").inc()
            self._rst_close(client)
            return
        if shape in ("blackhole", "half_open"):
            self._hold(idx, gen, client, shape)
            return
        try:
            upstream = socket.create_connection(
                (self.upstream_host, self.upstream_port), self._connect_timeout_s
            )
        except OSError:
            # upstream itself is down: surface as a closed connection (the
            # client's ordinary connect-error path), not a proxy crash
            try:
                client.close()
            except OSError:
                pass
            return
        with self._lock:
            self._open_socks.add(upstream)
        jitter_rng = random.Random(plan.jitter_seed)
        t_up = threading.Thread(
            target=self._pump_guarded, args=(plan, client, upstream, "c2u", None),
            name=f"netchaos-c2u-{idx}", daemon=True,
        )
        t_up.start()
        try:
            # response direction pumped on THIS thread (shaping applies here)
            self._pump(plan, upstream, client, "u2c", jitter_rng)
        finally:
            self._forget(upstream)
            try:
                upstream.close()
            except OSError:
                pass
            t_up.join(timeout=2.0)

    def _pump_guarded(self, plan, src, dst, direction, jitter_rng) -> None:
        try:  # YAMT011
            self._pump(plan, src, dst, direction, jitter_rng)
        except Exception:  # noqa: BLE001
            self._reg.counter("serve.thread_crashes").inc()

    def _pump(self, plan: FaultPlan, src: socket.socket,
              dst: socket.socket, direction: str, jitter_rng) -> None:
        """One direction of one connection, RE-DERIVING the plan from the
        live settings per chunk (plan_for is pure, so this is cheap and
        deterministic) — a mid-flight fault switch hits established
        keep-alive pipes too: a real partition does not spare open
        sockets."""
        src.settimeout(_SOCK_TIMEOUT_S)
        while not self._stop.is_set():
            plan = self.plan_for(plan.idx)
            shape = self._shape_now(plan)
            if shape == "reset":
                self._reg.counter("serve.netchaos.resets").inc()
                self._rst_close(dst)
                self._rst_close(src)
                return
            if shape == "blackhole" or (shape == "half_open" and direction == "u2c"):
                # the link eats everything: stop reading, stop forwarding
                self._stop.wait(_TICK_S)
                continue
            try:
                readable, _, _ = select.select([src], [], [], _TICK_S)
                if not readable:
                    continue
                data = src.recv(_CHUNK)
            except OSError:
                break
            if not data:
                if shape == "drop_response" and direction == "u2c":
                    # the upstream's FIN is response-direction traffic too:
                    # an asymmetric-loss link eats it, so the client keeps
                    # hanging instead of seeing a clean EOF
                    self._stop.wait(_TICK_S)
                    continue
                break
            # the fault may have switched while this thread was parked in
            # select: re-derive before any DELIVERY decision, and hold the
            # in-flight chunk through blackhole windows — a partition spares
            # no socket, and heal releases the stalled chunk, not drops it
            while not self._stop.is_set():
                plan = self.plan_for(plan.idx)
                shape = self._shape_now(plan)
                if shape == "blackhole" or (shape == "half_open" and direction == "u2c"):
                    self._stop.wait(_TICK_S)
                    continue
                break
            if self._stop.is_set():
                break
            if shape == "reset":
                self._reg.counter("serve.netchaos.resets").inc()
                self._rst_close(dst)
                self._rst_close(src)
                return
            if shape == "half_open" and direction == "c2u":
                continue  # consumed, never delivered
            if shape == "drop_response" and direction == "u2c":
                self._reg.counter("serve.netchaos.dropped_chunks").inc()
                continue  # the replica answered; the link lost it
            if direction == "u2c" and (plan.latency_s > 0 or plan.jitter_s > 0):
                self._reg.counter("serve.netchaos.delayed_chunks").inc()
                delay = plan.latency_s + (jitter_rng.uniform(0, plan.jitter_s)
                                          if plan.jitter_s > 0 else 0.0)
                self._stop.wait(delay)
            if direction == "u2c" and plan.bytes_per_s > 0:
                self._reg.counter("serve.netchaos.throttled_chunks").inc()
                self._stop.wait(len(data) / plan.bytes_per_s)
            try:
                dst.sendall(data)
            except OSError:
                break
        # half-close propagates: the peer's reader sees EOF, not a hang
        try:
            dst.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    @classmethod
    def from_config(cls, upstream_host: str, upstream_port: int, nc, **overrides):
        """Build from a config.NetChaosConfig block (serve.fleet.netchaos).
        The configured fault is NOT armed at construction — FleetChaos (or
        the bench) switches it on at its scheduled onset via set_fault."""
        kw = dict(
            seed=nc.seed,
            fault_rate=nc.fault_rate,
            latency_ms=nc.latency_ms,
            jitter_ms=nc.jitter_ms,
            bandwidth_kbps=nc.bandwidth_kbps,
            flap_period_s=nc.flap_period_s,
            flap_down_s=nc.flap_down_s,
        )
        kw.update(overrides)
        return cls(upstream_host, upstream_port, **kw)


class NetChaosTier:
    """One proxy per replica address, reconciled against the supervisor's
    membership notifications: cli/fleet.py wires ``on_change`` as
    ``router.set_backends(tier.route(addrs))`` so the router only ever
    speaks to replicas THROUGH their proxies — the bench's partition rounds
    and FleetChaos ``mode="partition"`` then pick a victim proxy and flip
    its fault live."""

    def __init__(self, *, seed: int = 0, proxy_factory=None, **proxy_kw):
        self._seed = seed
        self._proxy_kw = proxy_kw
        self._factory = proxy_factory or (
            lambda host, port, seed: NetChaosProxy(host, port, seed=seed, **proxy_kw).start()
        )
        self._lock = threading.Lock()
        self._proxies: dict[tuple[str, int], NetChaosProxy] = {}

    def route(self, addrs) -> list[tuple[str, int]]:
        """Map upstream addresses to proxy addresses (same order), creating
        proxies for new upstreams and stopping proxies whose upstream left
        the membership — the set_backends reconcile, one tier up."""
        want = [(h, int(p)) for h, p in addrs]
        out: list[tuple[str, int]] = []
        with self._lock:
            for key in [k for k in self._proxies if k not in want]:
                self._proxies.pop(key).stop()
            for i, key in enumerate(want):
                if key not in self._proxies:
                    # per-upstream seed offset: each link draws its own
                    # deterministic plan stream
                    self._proxies[key] = self._factory(key[0], key[1], self._seed + i)
                out.append(self._proxies[key].addr)
        return out

    def proxies(self) -> list[NetChaosProxy]:
        with self._lock:
            return list(self._proxies.values())

    def pick(self, rng: random.Random | None = None) -> NetChaosProxy | None:
        """One seeded-random proxy (the partition-chaos victim)."""
        ps = self.proxies()
        return (rng or random).choice(ps) if ps else None

    def stop(self) -> None:
        with self._lock:
            proxies, self._proxies = list(self._proxies.values()), {}
        for p in proxies:
            p.stop()
