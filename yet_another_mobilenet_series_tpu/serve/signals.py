"""Shared windowed-signal reader: the measured load signals every serving
control loop consumes.

Two controllers act on measured load — the fleet autoscaler
(serve/autoscale.py: add capacity) and the brownout ladder
(serve/brownout.py: trade quality for goodput when capacity cannot grow) —
and both must answer the same question: *how is the system doing RIGHT NOW,
not since boot?* The registry's histograms are cumulative, so a whole-run
quantile is anchored by every request ever served; a controller reading it
would see yesterday's calm long after today's storm began. The fix, factored
here so both controllers share ONE implementation instead of drifting
copies, is **bucket-count deltas**: snapshot the histogram's per-bucket
counts each tick, subtract the previous snapshot, and run the registry's own
quantile math (:func:`~..obs.registry.quantiles_from_counts`) over the
difference — the p99 of exactly the completions that landed since the last
tick, through the same interpolation /metrics exposes.

:class:`WindowedQuantile` is that one primitive. :class:`SignalReader`
bundles it with the other two live signals the controllers read:

- **queue depth** — an injected callable (the router's
  ``mean_queue_depth`` at the fleet tier; the admission controller's
  ``queued_total`` at the replica tier), read fresh each tick;
- **breaker state** — the ``serve.breaker_state`` gauge (0 closed / 1 open
  / 2 half-open): an open breaker means the engine itself is sick, which is
  overload evidence no latency window can show (rejected requests never
  reach the histogram).

Both consumers are pinned against this module: tests/test_fleet.py pins the
autoscaler's decisions unchanged across the refactor, and
tests/test_brownout.py drives the ladder from scripted
:class:`Signals` traces.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

from ..obs.registry import get_registry, quantiles_from_counts


@dataclasses.dataclass(frozen=True)
class Signals:
    """One tick's measured-load snapshot.

    ``p99_s`` is None when the window saw no completions (idle — only the
    queue/breaker signals speak); ``breaker_state`` uses the admission
    controller's encoding (0 closed / 1 open / 2 half-open).
    """

    p99_s: float | None
    queue_depth: float
    breaker_state: int

    @property
    def breaker_open(self) -> bool:
        return self.breaker_state == 1


class WindowedQuantile:
    """The q-quantile of a bucketed histogram's observations SINCE the last
    read — cumulative bucket counts differenced per tick, quantiled through
    the registry's own interpolation. Returns None for an empty window."""

    def __init__(self, name: str, quantile: float = 0.99):
        if not 0.0 < quantile < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {quantile}")
        self.name = name
        self.quantile = quantile
        self._hist = get_registry().histogram(name)
        self._counts_prev = self._hist.bucket_counts()

    def read(self) -> float | None:
        counts = self._hist.bucket_counts()
        delta = [a - b for a, b in zip(counts, self._counts_prev)]
        self._counts_prev = counts  # yamt-lint: disable=YAMT019 — each reader is single-owner by contract (SignalReader docstring): no concurrent read()
        if sum(delta) == 0:
            return None
        (q,) = quantiles_from_counts(self._hist.bounds, delta, (self.quantile,))
        return q


class SLOTracker:
    """Multi-window SLO burn rate over FEDERATED fleet signals.

    The SRE-workbook alerting shape: an SLO is an error budget (the
    fraction of requests allowed to be bad over the compliance period),
    and the *burn rate* is how fast the fleet is spending it — bad-request
    fraction divided by the budget, so burn 1.0 exhausts the budget
    exactly on schedule and burn 14 exhausts a 30-day budget in ~2 days.
    Alerting on ONE window is a trap: a short window pages on blips, a
    long window pages an hour late. The standard fix is requiring a SHORT
    and a LONG window to BOTH burn hot (:attr:`fast_burn`) — the short
    window proves it is still happening, the long window proves it is not
    a blip.

    Two budget dimensions, folded through ``max()`` into one burn number:

    - **error burn** — bad/total over the window vs ``error_budget``
      (bad = rejected + shed + failed, fed by obs/fleet.py from summed
      per-replica counter deltas);
    - **latency burn** — the fraction of scrape ticks whose federated
      windowed p99 breached ``target_p99_ms``, vs the same budget (a tick
      is this tracker's latency quantum: per-request latency SLIs would
      need per-request data federation does not ship).

    Driven by :meth:`observe` once per federation scrape
    (obs/fleet.py); read by the flight recorder (fast burn triggers an
    incident dump) and exported as ``fleet.slo_burn_rate.{short,long}``
    gauges. Single-owner by contract, like :class:`WindowedQuantile`: only
    the scrape loop calls ``observe``.
    """

    def __init__(
        self,
        *,
        target_p99_ms: float = 250.0,
        error_budget: float = 0.01,
        short_window_s: float = 30.0,
        long_window_s: float = 300.0,
        fast_burn: float = 14.0,
        clock: Callable[[], float] | None = None,
    ):
        if not 0.0 < error_budget < 1.0:
            raise ValueError(f"error_budget must be in (0, 1), got {error_budget}")
        if short_window_s <= 0 or long_window_s <= short_window_s:
            raise ValueError(
                f"windows must satisfy 0 < short < long, got "
                f"{short_window_s}/{long_window_s}")
        if fast_burn <= 0:
            raise ValueError(f"fast_burn must be > 0, got {fast_burn}")
        self.target_p99_s = target_p99_ms / 1e3
        self.error_budget = float(error_budget)
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.fast_burn_threshold = float(fast_burn)
        # injectable monotonic clock (tests drive time by hand)
        self._clock = clock or time.monotonic
        # per-tick samples: (t, total, bad, latency_breached: 0/1) — pruned
        # past the long window, so memory is bounded by tick rate x window
        self._ticks: list[tuple[float, int, int, int]] = []

    def observe(self, total: int, bad: int, p99_s: float | None = None) -> None:
        """Feed one scrape tick's WINDOWED deltas: ``total`` completed+bad
        requests and ``bad`` budget-burning ones since the previous tick,
        plus the tick's federated windowed p99 (None = idle tick, which
        cannot breach)."""
        now = self._clock()
        breached = 1 if (p99_s is not None and p99_s > self.target_p99_s) else 0
        self._ticks.append((now, max(int(total), 0), max(int(bad), 0), breached))
        horizon = now - self.long_window_s
        while self._ticks and self._ticks[0][0] < horizon:
            self._ticks.pop(0)

    def burn_rate(self, window_s: float) -> float:
        """Budget-burn multiple over the trailing ``window_s``: max of the
        error-fraction burn and the latency-breach-fraction burn. 0.0 with
        no traffic and no breaches."""
        horizon = self._clock() - window_s
        total = bad = ticks = breaches = 0
        for t, n, b, breach in self._ticks:
            if t < horizon:
                continue
            total += n
            bad += b
            ticks += 1
            breaches += breach
        error_burn = (bad / total / self.error_budget) if total else 0.0
        latency_burn = (breaches / ticks / self.error_budget) if ticks else 0.0
        return max(error_burn, latency_burn)

    @property
    def fast_burn(self) -> bool:
        """True when BOTH windows burn past the threshold — the page-now
        condition (and the flight recorder's slo_fast_burn trigger)."""
        return (self.burn_rate(self.short_window_s) >= self.fast_burn_threshold
                and self.burn_rate(self.long_window_s) >= self.fast_burn_threshold)

    def state(self) -> dict:
        """JSON view for /varz fleet snapshots and incident dumps."""
        return {
            "target_p99_ms": round(self.target_p99_s * 1e3, 3),
            "error_budget": self.error_budget,
            "burn_short": round(self.burn_rate(self.short_window_s), 4),
            "burn_long": round(self.burn_rate(self.long_window_s), 4),
            "fast_burn": self.fast_burn,
            "windows_s": [self.short_window_s, self.long_window_s],
            "ticks": len(self._ticks),
        }


class SignalReader:
    """Windowed per-class tail latency + queue depth + breaker state, read
    as one consistent :class:`Signals` snapshot per control tick.

    ``latency_family`` names the per-class histogram family
    (``serve.router.latency_seconds`` at the fleet tier,
    ``serve.latency_seconds`` at the replica tier); ``queue_depth_fn`` is
    the tier's backlog source (0 when None). Each :meth:`read` consumes the
    window — two controllers must each own their OWN reader.
    """

    def __init__(
        self,
        *,
        latency_family: str,
        signal_class: str = "interactive",
        quantile: float = 0.99,
        queue_depth_fn: Callable[[], float] | None = None,
    ):
        self._window = WindowedQuantile(f"{latency_family}.{signal_class}", quantile)
        self._queue_depth_fn = queue_depth_fn
        self._breaker_gauge = get_registry().gauge("serve.breaker_state")

    def window_p99_s(self) -> float | None:
        """The windowed tail alone (the autoscaler's original signal)."""
        return self._window.read()

    def queue_depth(self) -> float:
        return float(self._queue_depth_fn()) if self._queue_depth_fn is not None else 0.0

    def read(self) -> Signals:
        return Signals(
            p99_s=self._window.read(),
            queue_depth=self.queue_depth(),
            breaker_state=int(self._breaker_gauge.value),
        )
