"""Shared windowed-signal reader: the measured load signals every serving
control loop consumes.

Two controllers act on measured load — the fleet autoscaler
(serve/autoscale.py: add capacity) and the brownout ladder
(serve/brownout.py: trade quality for goodput when capacity cannot grow) —
and both must answer the same question: *how is the system doing RIGHT NOW,
not since boot?* The registry's histograms are cumulative, so a whole-run
quantile is anchored by every request ever served; a controller reading it
would see yesterday's calm long after today's storm began. The fix, factored
here so both controllers share ONE implementation instead of drifting
copies, is **bucket-count deltas**: snapshot the histogram's per-bucket
counts each tick, subtract the previous snapshot, and run the registry's own
quantile math (:func:`~..obs.registry.quantiles_from_counts`) over the
difference — the p99 of exactly the completions that landed since the last
tick, through the same interpolation /metrics exposes.

:class:`WindowedQuantile` is that one primitive. :class:`SignalReader`
bundles it with the other two live signals the controllers read:

- **queue depth** — an injected callable (the router's
  ``mean_queue_depth`` at the fleet tier; the admission controller's
  ``queued_total`` at the replica tier), read fresh each tick;
- **breaker state** — the ``serve.breaker_state`` gauge (0 closed / 1 open
  / 2 half-open): an open breaker means the engine itself is sick, which is
  overload evidence no latency window can show (rejected requests never
  reach the histogram).

Both consumers are pinned against this module: tests/test_fleet.py pins the
autoscaler's decisions unchanged across the refactor, and
tests/test_brownout.py drives the ladder from scripted
:class:`Signals` traces.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from ..obs.registry import get_registry, quantiles_from_counts


@dataclasses.dataclass(frozen=True)
class Signals:
    """One tick's measured-load snapshot.

    ``p99_s`` is None when the window saw no completions (idle — only the
    queue/breaker signals speak); ``breaker_state`` uses the admission
    controller's encoding (0 closed / 1 open / 2 half-open).
    """

    p99_s: float | None
    queue_depth: float
    breaker_state: int

    @property
    def breaker_open(self) -> bool:
        return self.breaker_state == 1


class WindowedQuantile:
    """The q-quantile of a bucketed histogram's observations SINCE the last
    read — cumulative bucket counts differenced per tick, quantiled through
    the registry's own interpolation. Returns None for an empty window."""

    def __init__(self, name: str, quantile: float = 0.99):
        if not 0.0 < quantile < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {quantile}")
        self.name = name
        self.quantile = quantile
        self._hist = get_registry().histogram(name)
        self._counts_prev = self._hist.bucket_counts()

    def read(self) -> float | None:
        counts = self._hist.bucket_counts()
        delta = [a - b for a, b in zip(counts, self._counts_prev)]
        self._counts_prev = counts  # yamt-lint: disable=YAMT019 — each reader is single-owner by contract (SignalReader docstring): no concurrent read()
        if sum(delta) == 0:
            return None
        (q,) = quantiles_from_counts(self._hist.bounds, delta, (self.quantile,))
        return q


class SignalReader:
    """Windowed per-class tail latency + queue depth + breaker state, read
    as one consistent :class:`Signals` snapshot per control tick.

    ``latency_family`` names the per-class histogram family
    (``serve.router.latency_seconds`` at the fleet tier,
    ``serve.latency_seconds`` at the replica tier); ``queue_depth_fn`` is
    the tier's backlog source (0 when None). Each :meth:`read` consumes the
    window — two controllers must each own their OWN reader.
    """

    def __init__(
        self,
        *,
        latency_family: str,
        signal_class: str = "interactive",
        quantile: float = 0.99,
        queue_depth_fn: Callable[[], float] | None = None,
    ):
        self._window = WindowedQuantile(f"{latency_family}.{signal_class}", quantile)
        self._queue_depth_fn = queue_depth_fn
        self._breaker_gauge = get_registry().gauge("serve.breaker_state")

    def window_p99_s(self) -> float | None:
        """The windowed tail alone (the autoscaler's original signal)."""
        return self._window.read()

    def queue_depth(self) -> float:
        return float(self._queue_depth_fn()) if self._queue_depth_fn is not None else 0.0

    def read(self) -> Signals:
        return Signals(
            p99_s=self._window.read(),
            queue_depth=self.queue_depth(),
            breaker_state=int(self._breaker_gauge.value),
        )
