"""Quantized serving: the uint8 wire and post-training int8 weights.

The serving request path used to move float32 end to end — every pixel cost
4 bytes over H2D and every folded weight sat in HBM at full width — even
though the data pipeline itself notes pixels round to u8 under JPEG decode
noise (config.py ``data.transfer_uint8``). This module is the shared
substrate of the two parity-gated rungs that shrink those bytes:

**Rung 1 — the uint8 wire** (``serve.quant.wire="uint8"``). Clients send RAW
pixels (0..255); they stage, pool, and transfer as ``uint8`` — exactly 1/4
of the f32 wire's bytes per image — and the compiled executable
denormalizes ON DEVICE with the pipeline's mean/std before the folded
forward (one dispatch, no host normalize pass). The denormalization
constants are precomputed f32:

    scale = 1 / (255 * std)          shift = -mean / std
    normalized = u8.astype(f32) * scale [+ shift]

:func:`normalize_reference` is the host-side definition of what a u8 wire
value STANDS FOR — the f32 pixels the f32 wire would have carried — and the
device prelude (:func:`denormalize_device`) computes the identical
expression. Parity vs the f32 wire therefore has two regimes, both pinned:

- ``shift == 0`` (zero mean): the prelude is a SINGLE per-channel multiply,
  which XLA cannot re-associate — device output is **bitwise identical** to
  the host reference (probed and pinned in tests/test_quant.py). This is
  the "fold is exact" case: with no additive term the scale even commutes
  exactly with the stem conv, but the single-multiply prelude is chosen
  over weight-folding because bitwise beats one-f32-rounding.
- nonzero mean (e.g. the ImageNet defaults): XLA may fuse the multiply+add
  into an FMA (measured: 1-ulp input deltas on CPU), so parity is gated on
  a measured max-abs logit delta <= ``serve.quant.wire_atol`` instead. The
  additive shift can NOT be folded through the zero-padded stem conv at
  all — border pixels see fewer shift contributions than interior ones —
  which is why the general case is a fused in-program prelude, not a
  weight transform.

**Rung 2 — post-training int8 weights** (``serve.quant.weights="int8"``).
An export-time pass (:func:`quantize_folded`) quantizes every folded conv /
dense weight with per-OUTPUT-channel symmetric scales (``scale_c =
max|w[..., c]| / 127``); the bundle stores ``w_q`` (int8) + ``w_scale``
(f32) + the f32 bias, so the artifact and the device-resident param tree
shrink ~4x, and :func:`..export.apply_folded` dequantizes IN-PROGRAM
(``w_q.astype(f32) * w_scale``) — HBM holds int8, the MXU still computes
f32/bf16. Export is gated: :func:`calibrate_and_quantize` runs a held-out
calibration batch through both forwards and refuses to write an artifact
whose top-1 agreement with the f32 bundle falls below
``serve.quant.int8_top1_min`` (:class:`QuantParityError`), recording
per-stage activation ranges + the measured agreement as provenance the
bundle carries (``meta.json["quant"]``). Squeeze-excite gates stay f32
(<1% of weights, and the sigmoid gate is the most range-sensitive spot).

Module-level imports are numpy-only on purpose: the batcher imports this
for :func:`coerce_wire`, and supervisors (cli/fleet.py) must keep importing
serve pieces without dragging jax in. jax is imported inside the functions
that trace device code.
"""

from __future__ import annotations

import numpy as np

WIRE_DTYPES = ("float32", "uint8")
WEIGHT_DTYPES = ("float32", "int8")

# paths (relative key names inside a folded tree) that stay f32 under int8
# weight quantization: SE gates are tiny and range-sensitive
_QUANT_SKIP_KEYS = ("se",)


class QuantParityError(RuntimeError):
    """The quantized artifact failed its parity gate (uint8-wire logit delta
    above ``wire_atol``, or int8 top-1 agreement below ``int8_top1_min``) —
    export refuses to write an artifact that serves wrong answers."""


def wire_np_dtype(wire: str) -> type:
    """numpy dtype of a wire mode name (staging buffers, client coercion)."""
    if wire not in WIRE_DTYPES:
        raise ValueError(f"serve.quant.wire must be one of {WIRE_DTYPES}, got {wire!r}")
    return {"float32": np.float32, "uint8": np.uint8}[wire]


def denorm_constants(mean, std) -> tuple[np.ndarray, np.ndarray]:
    """(scale, shift) f32 per-channel constants of the on-device
    denormalization ``u8 * scale + shift`` == ``(u8/255 - mean) / std``.
    ``mean=None``/``std=None`` mean the identity pipeline (mean 0, std 1):
    the wire then stands for plain ``u8 * (1/255)`` pixels."""
    mean = np.zeros(3, np.float32) if mean is None else np.asarray(mean, np.float32)
    std = np.ones(3, np.float32) if std is None else np.asarray(std, np.float32)
    if mean.shape != (3,) or std.shape != (3,):
        raise ValueError(f"mean/std must be 3-channel, got {mean.shape}/{std.shape}")
    if np.any(std <= 0):
        raise ValueError(f"std must be positive, got {std}")
    scale = (np.float32(1.0) / (np.float32(255.0) * std)).astype(np.float32)
    shift = (-mean / std).astype(np.float32)
    return scale, shift


def shift_free(shift: np.ndarray) -> bool:
    """True when the denorm has no additive term — the regime where the u8
    wire is BITWISE-identical to the host-normalized f32 wire (the prelude
    is one multiply; nothing for XLA to re-associate)."""
    return bool(np.all(shift == 0.0))


def normalize_reference(images: np.ndarray, mean=None, std=None) -> np.ndarray:
    """Host-side f32 pixels a u8 wire batch stands for — THE reference the
    parity gates compare against. Computes exactly the expression
    :func:`denormalize_device` traces (same constants, same op order) so the
    shift-free case is bitwise and the general case differs only by the
    backend's FMA formation."""
    scale, shift = denorm_constants(mean, std)
    x = images.astype(np.float32) * scale
    if not shift_free(shift):
        x = x + shift
    return x


def denormalize_device(x, scale: np.ndarray, shift: np.ndarray):
    """The in-program denorm prelude (traced inside the engine's compiled
    forward): cast + per-channel multiply, plus the shift only when nonzero
    — a zero add would cost nothing numerically but would invite FMA
    formation that breaks the shift-free bitwise claim.

    Traced at three sites, all producing the SAME prelude HLO: the K=1
    per-chunk executables, the fused-K scan body, and the ring scan body
    (serve/ring.py) — u8 ring slots cross H2D raw and denormalize inside
    the scan, so a ring window of u8 slots keeps both the 4x wire saving
    and the shift-free bitwise parity."""
    import jax.numpy as jnp

    h = x.astype(jnp.float32) * jnp.asarray(scale)
    if not shift_free(shift):
        h = h + jnp.asarray(shift)
    return h


def coerce_wire(image: np.ndarray, np_dtype) -> np.ndarray:
    """Coerce a client array to the wire dtype. float32 wire: the historical
    ``np.asarray(image, np.float32)``. uint8 wire: integer inputs convert
    exactly; float inputs (e.g. JSON bodies parsed as floats) are
    rounded-and-clipped to the pixel range — ``astype(uint8)`` alone would
    TRUNCATE and wrap negatives, silently corrupting pixels."""
    img = np.asarray(image)
    if img.dtype == np_dtype:
        return img
    if np_dtype == np.uint8 and np.issubdtype(img.dtype, np.floating):
        return np.clip(np.rint(img), 0, 255).astype(np.uint8)
    return img.astype(np_dtype)


# ---------------------------------------------------------------------------
# int8 weights: per-output-channel symmetric post-training quantization
# ---------------------------------------------------------------------------


def quantize_array_int8(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(w_q int8, scale f32) with per-OUTPUT-channel symmetric scales.
    Output channels are the LAST axis of every folded weight in this
    codebase — HWIO conv kernels (dense, grouped, and depthwise alike) and
    (in, out) dense matrices — so one reduction axis rule covers all of
    them: ``scale_c = max|w[..., c]| / 127`` (1.0 for an all-zero channel,
    so dequantization never divides by zero)."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w.reshape(-1, w.shape[-1])), axis=0)
    scale = np.where(amax > 0, amax / np.float32(127.0), np.float32(1.0)).astype(np.float32)
    w_q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return w_q, scale


def dequantize_array(w_q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """Host-side inverse of :func:`quantize_array_int8` (tests and the
    calibration forward; the serving engine dequantizes in-program)."""
    return w_q.astype(np.float32) * np.asarray(scale, np.float32)


def _is_weight_pair(v) -> bool:
    """A folded conv/dense leaf dict: {'w': (..., C) float, 'b': (C,)}."""
    return (
        isinstance(v, dict)
        and set(v) == {"w", "b"}
        and getattr(v["w"], "ndim", 0) in (2, 4)
    )


def quantize_folded(folded: dict, _path: str = "") -> tuple[dict, int]:
    """Folded f32 param tree -> int8-weight tree: every {'w','b'} conv/dense
    pair becomes {'w_q' int8, 'w_scale' f32, 'b' f32}; SE subtrees (and
    anything that is not a weight pair) pass through untouched. Returns the
    new tree and the number of quantized tensors. Deterministic: the scales
    are a pure function of the weights."""
    out: dict = {}
    n = 0
    for k, v in folded.items():
        path = f"{_path}/{k}" if _path else k
        if k in _QUANT_SKIP_KEYS:
            out[k] = v
        elif _is_weight_pair(v):
            w_q, scale = quantize_array_int8(v["w"])
            out[k] = {"w_q": w_q, "w_scale": scale, "b": np.asarray(v["b"], np.float32)}
            n += 1
        elif isinstance(v, dict):
            out[k], sub_n = quantize_folded(v, path)
            n += sub_n
        else:
            out[k] = v
    return out, n


def tree_nbytes(tree: dict) -> int:
    """Total array bytes of a (possibly nested) param tree — the resident-
    byte accounting the int8 export's provenance records."""
    total = 0
    for v in tree.values():
        if isinstance(v, dict):
            total += tree_nbytes(v)
        else:
            total += int(getattr(np.asarray(v), "nbytes", 0))
    return total


def calibrate_and_quantize(
    net,
    folded: dict,
    calib_images: np.ndarray,
    *,
    top1_min: float = 0.98,
    calib_meta: dict | None = None,
) -> tuple[dict, dict]:
    """The gated export-time int8 pass: quantize the folded weights, run the
    held-out calibration batch through BOTH forwards (eagerly — this is a
    one-off export step, not the serving path), and refuse
    (:class:`QuantParityError`) unless top-1 agreement with the f32 bundle
    meets ``top1_min``. Returns ``(quantized_tree, report)`` where the
    report is the provenance block the bundle's ``meta.json`` carries:
    quantized-tensor count, resident-byte shrink, per-stage activation
    ranges from the calibration batch, the measured top-1 agreement and the
    max-abs logit delta. Deterministic: same weights + same batch -> same
    scales, same ranges, same verdict."""
    from .export import apply_folded

    calib_images = np.asarray(calib_images, np.float32)
    if calib_images.ndim != 4 or calib_images.shape[0] < 1:
        raise ValueError(f"calibration batch must be (N, S, S, 3), got {calib_images.shape}")
    quantized, n_tensors = quantize_folded(folded)
    if n_tensors == 0:
        raise ValueError("int8 export found no quantizable weight pairs in the folded tree")
    ranges: dict[str, tuple[float, float]] = {}
    ref = np.asarray(apply_folded(net, folded, calib_images, collect=ranges))
    got = np.asarray(apply_folded(net, quantized, calib_images))
    agree = float(np.mean(np.argmax(got, -1) == np.argmax(ref, -1)))
    delta = float(np.max(np.abs(got - ref)))
    report = {
        "weights": "int8",
        "scheme": "per_output_channel_symmetric",
        "quantized_tensors": n_tensors,
        "bytes_f32": tree_nbytes(folded),
        "bytes_int8": tree_nbytes(quantized),
        "top1_agreement": agree,
        "top1_min": float(top1_min),
        "max_abs_logit_delta": delta,
        "calib": {
            "images": int(calib_images.shape[0]),
            "image_size": int(calib_images.shape[1]),
            "activation_ranges": {k: [float(lo), float(hi)] for k, (lo, hi) in ranges.items()},
            **(calib_meta or {}),
        },
    }
    if agree < top1_min:
        raise QuantParityError(
            f"int8 export failed its parity gate: top-1 agreement {agree:.4f} < "
            f"{top1_min} on the {calib_images.shape[0]}-image calibration batch "
            f"(max |logit delta| {delta:.4g}); the f32 bundle stays the servable artifact"
        )
    return quantized, report
