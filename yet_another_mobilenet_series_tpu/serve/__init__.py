"""Serving subsystem: pruned-model export + AOT-batched inference engine.

The training stack ends at a checkpoint; this package turns that checkpoint
into a deployable artifact and serves it (the "serves heavy traffic" half of
the ROADMAP north star, and the LANA/Kernel-Looping argument from PAPERS.md:
peak inference wants a dedicated representation + dispatch layer, not the
training graph re-run with train=False):

- :mod:`.export` — hard-apply prune masks (nas/rematerialize surgery),
  select EMA weights, FOLD BatchNorm running stats + affine into the
  adjacent conv weights (a real weight transform), and emit an
  ``InferenceBundle`` (spec JSON via models/serialize schema v2 + npz
  weights) — plus the folded forward pass the engine runs.
- :mod:`.quant` — quantized serving substrate: the uint8 wire's
  denormalization constants + host reference + client coercion, and the
  gated post-training int8 weight pass (per-output-channel symmetric
  scales, calibration provenance, top-1 agreement gate). Module-level
  imports are numpy-only so jax-free supervisors can keep importing
  batcher/client.
- :mod:`.engine` — bucketed batch shapes with pad-and-slice dispatch to an
  AOT-compiled ``(bucket, image_size)`` executable cache, async no-sync
  dispatch (``predict_async`` -> ``PendingPrediction``), reused staging
  buffers, warmup precompile, input-buffer donation, optional data-parallel
  sharding over parallel/mesh.
- :mod:`.batcher` — thread-based micro-batching request queue: coalesce up
  to ``max_batch`` or ``max_wait_ms``, bounded queue for backpressure,
  per-request deadlines with timeout shedding.
- :mod:`.pipeline` — the pipelined producer/consumer batcher: a collect/
  dispatch thread keeps the device fed through ``predict_async`` while a
  completion thread syncs results, bounded by a ``max_inflight`` window
  (continuous batching; the serving default).
- :mod:`.admission` — the resilience edge: per-class (interactive / batch /
  best_effort) weighted admission with deadline-aware reject-on-arrival,
  bounded retry with jittered backoff for transient engine failures, and a
  consecutive-failure circuit breaker with a single half-open probe.
- :mod:`.frontend` — the stdlib-only loopback HTTP front door
  (``POST /predict`` with priority + deadline headers, ``GET /healthz``
  with breaker + queue state) behind ``cli/serve.py --listen``.
- :mod:`.faults` — deterministic, seeded fault injection around any engine
  (failure rates, fail-N-then-recover, added latency, hang-until-event) so
  every recovery path above is testable and benchable.
- :mod:`.client` — the connection-reused, typed-error HTTP client every
  frontend caller shares (router, hedger, benches): keep-alive per thread,
  replica verdicts surfaced as :class:`~.client.ClientHTTPError` with the
  wire status + tag.
- :mod:`.router` — the fleet tier: weighted routing over N replica
  frontends driven by polled ``/healthz`` (queue depth, breaker, identity),
  ejection/readmission, transport-level retry, hedging integration. Speaks
  the admission protocol, so a :class:`~.frontend.Frontend` serves it
  directly and a fleet is externally indistinguishable from one replica.
- :mod:`.hedge` — request hedging: duplicate a straggler to a second
  replica at a timer derived from the measured per-class latency p99;
  first answer wins, the loser is dropped idempotently.
- :mod:`.autoscale` — the control thread scaling replica count off the
  measured tail-latency + queue-depth families with cooldown hysteresis
  (cli/fleet.py is the supervisor it drives).
- :mod:`.signals` — the shared windowed-signal reader both control loops
  consume: per-class tail latency off registry bucket-count deltas (the
  p99 of THIS tick's completions, not history), queue depth, breaker
  state.
- :mod:`.netchaos` — socket-level network chaos: a seeded stdlib-socket TCP
  fault-injection proxy between router and replica (blackhole, reset,
  half-open, latency/jitter, throttle, asymmetric response loss, timed
  flaps), so every PARTITION shape is reproducible on one box without
  root/iptables — the wire-level twin of :mod:`.faults`.
- :mod:`.brownout` — the graceful-degradation ladder under sustained
  overload: L0 (healthy) → L5 (interactive-only survival), stepping off
  the measured signals with asymmetric hysteresis — hedging off first,
  then fill-or-flush batching, then class shedding with ``Retry-After``,
  then tightened deadline admission; one level down per cooldown on
  recovery, so quality returns as deliberately as it left.

Everything is instrumented through obs/ (``serve/*`` spans, queue-wait and
run-latency histograms, request/shed counters), so scripts/obs_report.py
renders serving runs exactly like training runs. docs/SERVING.md is the
operator guide; ``cli/serve.py`` + the ``serve:`` config block are the entry
point.
"""

# Lazy re-exports (PEP 562): .export drags in jax, but the fleet supervisor
# (cli/fleet.py) imports sibling serve modules (frontend, router, client)
# and must stay jax-free — the replicas own the device, the parent owns
# policy. Importing the package therefore costs nothing until an export
# symbol is actually touched.
_EXPORTS = ("InferenceBundle", "apply_folded", "export_bundle", "fold_network", "load_bundle")


def __getattr__(name):
    if name in _EXPORTS:
        from . import export

        return getattr(export, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_EXPORTS))
