"""Priority/QoS admission control + resilience edge in front of the batcher.

The batcher (serve/batcher.py, serve/pipeline.py) gives every request the
same FIFO treatment and fails a batch's futures the moment the engine throws.
Correct, but production traffic is not uniform and engines are not immortal:
interactive requests must not starve behind a best-effort flood, a transient
engine failure should cost a retry, not a user error, and a SICK engine must
stop eating every queued request. Per Kernel Looping (PAPERS.md,
arXiv:2410.23668) the device-feeding path must stay non-blocking, so ALL of
this logic lives at **admission time** (synchronous, before the queue) and
**completion time** (future callbacks) — never inside the dispatch loop.

:class:`AdmissionController` wraps a started batcher and adds three layers:

**1. Per-class weighted admission.** Three priority classes —
``interactive`` / ``batch`` / ``best_effort`` — each holding a weighted
share of the queue (``weights``): a class at its quota is rejected with
:class:`ClassQueueFull` while other classes still admit, so overload sheds
the cheap traffic first and a flood in one class can never starve another.
On top of quotas, **deadline-aware rejection at arrival**: an EWMA of
observed request latency predicts the wait a new request faces; a request
whose deadline the prediction already blows is rejected with
:class:`DeadlineUnmeetable` immediately — reject-on-arrival beats
shed-after-queue (the request never burns a queue slot or a bucket row).

**2. Bounded retry with jittered backoff.** The folded inference forward is
pure — a retry cannot double-apply anything — so a transient engine failure
(the only exception class that retries; deadline sheds and rejections do
not) re-submits up to ``max_retries`` times with exponentially growing,
jitter-desynchronized backoff, counted in ``serve.retries``. Retries stop
early when the request's own deadline passes or the breaker opens.

**3. Circuit breaker.** ``breaker_threshold`` CONSECUTIVE engine failures
open the breaker: every submit fails fast with :class:`BreakerOpen` (no
queue time, no engine load) for ``breaker_cooldown_s``, after which the
breaker goes half-open and admits exactly ONE probe request; the probe's
outcome closes the breaker (success) or re-opens it for another cooldown
(failure). State is exported as the ``serve.breaker_state`` gauge
(0 closed / 1 open / 2 half-open) and in :meth:`AdmissionController.state`
— the payload behind ``GET /healthz`` (serve/frontend.py).

Instrumentation (obs/): per-class ``serve.latency_seconds.<class>``
histograms and ``serve.requests.<class>`` / ``serve.completed.<class>`` /
``serve.rejected.<class>`` counters; cause-split ``serve.rejected_breaker``
/ ``serve.rejected_deadline`` / ``serve.rejected_class_full`` counters;
``serve.retries`` (+ per class), ``serve.engine_failures``,
``serve.breaker_opens``, and the ``serve.breaker_state`` gauge.
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import Future, InvalidStateError

from ..obs.registry import get_registry
from .batcher import DeadlineExceeded, DrainTimeout, QueueFull
from .context import RequestContext

# the QoS taxonomy, cheapest-to-shed last; weights align with this order
CLASSES = ("interactive", "batch", "best_effort")

BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN = 0, 1, 2
_BREAKER_NAMES = {BREAKER_CLOSED: "closed", BREAKER_OPEN: "open", BREAKER_HALF_OPEN: "half_open"}


class BreakerOpen(RuntimeError):
    """Rejected at arrival: the circuit breaker is open (engine failure
    streak); retry after the cooldown."""


class BrownoutShed(RuntimeError):
    """Rejected at arrival: the brownout ladder (serve/brownout.py) is
    shedding this priority class to protect interactive goodput under
    sustained overload. Maps to 503 with a ``Retry-After`` hint — the
    server is healthy, just saturated; come back, don't eject it.

    ``retry_after_s`` rides the exception so the frontend can emit the
    header and the router can tell backpressure from death."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineUnmeetable(RuntimeError):
    """Rejected at arrival: the predicted wait already exceeds the request's
    deadline — shedding now is strictly cheaper than shedding after queueing."""


class ClassQueueFull(QueueFull):
    """Rejected at arrival: this priority class is at its weighted queue
    share (other classes may still be admitting)."""


class UnknownModel(ValueError):
    """Rejected at arrival: the request's ``X-Model`` names a model this
    replica does not serve. Typed so the frontend answers 400 with the
    served-model list in the body (never a KeyError-shaped 500) and the
    client surfaces a typed :class:`~.client.ClientHTTPError` tag.
    ``served`` rides the exception for the error body."""

    def __init__(self, model: str, served):
        self.model = model
        self.served = tuple(served)
        super().__init__(
            f"unknown model {model!r}; served: {', '.join(self.served) or '(none)'}")


class ModelQueueFull(QueueFull):
    """Rejected at arrival: this model is at its configured in-system quota
    (serve.zoo.quotas) — other models may still be admitting, so a burst on
    one zoo tenant can never starve the others."""


class CircuitBreaker:
    """Consecutive-failure breaker with a single half-open probe.

    Thread-safe; transitions are driven by :meth:`allow` (at admission) and
    :meth:`on_success` / :meth:`on_failure` (at completion).
    """

    def __init__(self, threshold: int, cooldown_s: float):
        if threshold < 1:
            raise ValueError(f"breaker_threshold must be >= 1, got {threshold}")
        self._threshold = threshold
        self._cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._streak = 0
        self._opened_at = 0.0
        self._probe_pending = False
        self._reg = get_registry()
        self._reg.gauge("serve.breaker_state").set(BREAKER_CLOSED)

    def _set_state(self, state: int) -> None:
        self._state = state
        self._reg.gauge("serve.breaker_state").set(state)

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    @property
    def state_name(self) -> str:
        return _BREAKER_NAMES[self.state]

    def allow(self) -> tuple[bool, bool]:
        """(admit?, is_probe?) for one arriving request."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True, False
            if self._state == BREAKER_OPEN:
                if time.perf_counter() - self._opened_at < self._cooldown_s:
                    return False, False
                self._set_state(BREAKER_HALF_OPEN)
                self._probe_pending = True
                return True, True  # the cooldown's first arrival IS the probe
            # half-open: one probe outstanding at a time
            if self._probe_pending:
                return False, False
            self._probe_pending = True
            return True, True

    def cancel_probe(self) -> None:
        """The admitted probe was rejected downstream before reaching the
        engine: free the probe slot for the next arrival."""
        with self._lock:
            self._probe_pending = False

    def on_success(self, probe: bool) -> None:
        with self._lock:
            self._streak = 0
            if probe:
                self._probe_pending = False
                self._set_state(BREAKER_CLOSED)

    def on_failure(self, probe: bool) -> None:
        with self._lock:
            if probe:
                self._probe_pending = False
                self._open()
                return
            if self._state != BREAKER_CLOSED:
                return  # failures of pre-open stragglers don't re-arm the clock
            self._streak += 1
            if self._streak >= self._threshold:
                self._open()

    def _open(self) -> None:
        self._streak = 0
        self._opened_at = time.perf_counter()
        if self._state != BREAKER_OPEN:
            self._set_state(BREAKER_OPEN)
            self._reg.counter("serve.breaker_opens").inc()


class _Pending:
    """Admission-side bookkeeping for one in-system request (survives
    retries — the class quota slot is held until final resolution)."""

    __slots__ = ("cls", "image", "t_submit", "t_deadline", "retries_left", "probe", "attempt", "ctx",
                 "model")

    def __init__(self, cls, image, deadline_s, retries_left, probe, ctx, model=None):
        self.cls = cls
        self.image = image
        self.t_submit = time.perf_counter()
        self.t_deadline = None if deadline_s is None else self.t_submit + deadline_s
        self.retries_left = retries_left
        self.probe = probe
        self.attempt = 0
        self.ctx = ctx
        self.model = model


class AdmissionController:
    """QoS admission + retry + breaker around a started batcher.

    ``submit`` mirrors the batcher's API (image, deadline) plus ``priority``
    and returns a Future that ALWAYS resolves: to logits, or to a typed
    rejection/shed/failure — never a silent hang.
    """

    def __init__(
        self,
        batcher,
        *,
        weights=(8.0, 3.0, 1.0),
        default_class: str = "interactive",
        max_retries: int = 2,
        retry_backoff_ms: float = 5.0,
        retry_jitter: float = 0.5,
        breaker_threshold: int = 5,
        breaker_cooldown_s: float = 1.0,
        ewma_alpha: float = 0.2,
        reject_unmeetable: bool = True,
        predictor: str = "ewma",
        predictor_quantile: float = 0.9,
        seed: int = 0,
        heartbeat=None,
        models=None,
        default_model: str | None = None,
        model_quotas=None,
    ):
        if predictor not in ("ewma", "quantile"):
            raise ValueError(f"predictor must be 'ewma' or 'quantile', got {predictor!r}")
        if len(weights) != len(CLASSES):
            raise ValueError(f"need one weight per class {CLASSES}, got {weights}")
        if default_class not in CLASSES:
            raise ValueError(f"default_class {default_class!r} not in {CLASSES}")
        self._batcher = batcher
        self._default_class = default_class
        self._max_retries = max(0, int(max_retries))
        self._backoff_s = retry_backoff_ms / 1e3
        self._jitter = retry_jitter
        self._alpha = ewma_alpha
        self._reject_unmeetable = reject_unmeetable
        self._predictor = predictor
        self._predictor_q = float(predictor_quantile)
        self._heartbeat = heartbeat  # e.g. StallWatchdog.arm — beats per completion
        self._rng = random.Random(seed)
        self.breaker = CircuitBreaker(breaker_threshold, breaker_cooldown_s)
        depth = batcher._q.maxsize or 256
        total_w = float(sum(weights))
        self._quota = {
            cls: max(1, int(round(depth * w / total_w))) for cls, w in zip(CLASSES, weights)
        }
        self._weights = dict(zip(CLASSES, weights))
        self._lock = threading.Lock()
        self._in_queue = {cls: 0 for cls in CLASSES}
        self._ewma_s: float | None = None
        # brownout policy pushed by serve/brownout.py (all neutral at L0):
        # classes rejected at the door, a multiplier tightening the
        # deadline-admission margin, and whether transient-failure retries
        # still run (L5 survival mode spends no capacity on second chances)
        self._shed_classes: frozenset[str] = frozenset()
        self._deadline_margin = 1.0
        self._retries_enabled = True
        self._brownout_level = 0
        self._brownout_retry_after_s = 1.0
        # rid -> RequestContext for every request currently in the system:
        # the hang report's "whose request is wedged" section reads this
        self._inflight_ctx: dict[int, RequestContext] = {}
        # zoo tenancy (serve/zoo.py): the served-model set (None = legacy
        # single-model process, X-Model left unvalidated here), the name
        # unqualified requests resolve to, and optional per-model in-system
        # quotas so a burst on one tenant can never starve the others
        self._models: tuple[str, ...] | None = tuple(models) if models else None
        if default_model is not None and self._models is not None and default_model not in self._models:
            raise ValueError(f"default_model {default_model!r} not in served set {self._models}")
        self._default_model = default_model or (self._models[0] if self._models else None)
        self._model_quota = {k: int(v) for k, v in dict(model_quotas or {}).items()}
        self._in_model: dict[str, int] = {}
        self._reg = get_registry()

    # -- the arrival-time wait predictor ------------------------------------

    def _observe(self, cls: str, latency_s: float) -> None:
        self._reg.histogram(f"serve.latency_seconds.{cls}").observe(latency_s)
        with self._lock:
            self._ewma_s = (
                latency_s if self._ewma_s is None
                else self._alpha * latency_s + (1 - self._alpha) * self._ewma_s
            )

    def predicted_wait_s(self, cls: str | None = None) -> float:
        """Expected time-to-answer for a request admitted NOW: a per-request
        latency estimate scaled by the backlog in units of engine batches.
        0 until the first completion lands (no data — admit optimistically).

        Two estimators (``predictor`` config): ``ewma`` (the original
        smoothed mean — tracks the center, blind to the tail) and
        ``quantile`` (the ``predictor_quantile`` of the class's bucketed
        ``serve.latency_seconds.<class>`` histogram — a p90-based predictor
        sheds on TAIL latency, which is what deadlines are actually about;
        FLASH/LANA: decide on measured latency, not a proxy). The quantile
        mode falls back to the EWMA until the class histogram has data."""
        with self._lock:
            ewma = self._ewma_s
            backlog = sum(self._in_queue.values())
        per_request = ewma
        if self._predictor == "quantile":
            hist = self._reg.histogram(f"serve.latency_seconds.{cls or self._default_class}")
            if hist.count:
                per_request = hist.quantile(self._predictor_q)
        if per_request is None:
            return 0.0
        per_batch = max(getattr(self._batcher, "_max_batch", 1), 1)
        # the brownout deadline margin (> 1 at L4+) inflates the estimate,
        # so deadline-carrying requests shed EARLIER under overload — the
        # predictor lags a storm by design (it only learns from completions)
        with self._lock:
            margin = self._deadline_margin
        return per_request * (1.0 + backlog / per_batch) * margin

    # -- brownout actuation (serve/brownout.py pushes, never reads) ----------

    def apply_brownout(self, policy) -> None:
        """Install one :class:`~.brownout.BrownoutPolicy` atomically: the
        classes to reject at the door, the deadline-margin multiplier, and
        the retry switch. Called from the controller thread on every ladder
        transition; in-flight requests keep the policy they admitted under."""
        with self._lock:
            self._shed_classes = frozenset(policy.shed_classes)
            self._deadline_margin = float(policy.deadline_margin)
            self._retries_enabled = bool(policy.retries)
            self._brownout_level = int(policy.level)
            self._brownout_retry_after_s = float(policy.retry_after_s)

    def queued_total(self) -> float:
        """Total admitted-and-unresolved requests across classes — the
        replica-tier backlog signal (serve/signals.py queue_depth_fn)."""
        with self._lock:
            return float(sum(self._in_queue.values()))

    # -- client side --------------------------------------------------------

    def submit(
        self,
        image,
        *,
        priority: str | None = None,
        deadline_ms: float | None = None,
        ctx: RequestContext | None = None,
        model: str | None = None,
    ) -> Future:
        cls = priority or self._default_class
        if cls not in CLASSES:
            raise ValueError(f"unknown priority class {cls!r}; valid: {CLASSES}")
        # model resolution + validation FIRST: a client naming an unserved
        # model is a 400-class error regardless of brownout/breaker state —
        # reject before any policy machinery can spend a probe or a slot
        model = model or (ctx.model if ctx is not None else None) or self._default_model
        if self._models is not None and model is not None and model not in self._models:
            self._reject(cls, "serve.rejected_unknown_model")
            raise UnknownModel(model, self._models)
        if ctx is None:  # direct callers get an id too; the frontend mints its own
            ctx = RequestContext.mint(cls, deadline_ms, model=model)
        elif ctx.model is None:
            ctx.model = model
        # brownout class shed FIRST (before the breaker can spend a probe
        # slot): the cheapest possible rejection — no quota, no queue, no
        # engine load, and a Retry-After so well-behaved clients back off
        with self._lock:
            shed_classes = self._shed_classes
            level = self._brownout_level
            retry_after_s = self._brownout_retry_after_s
        if cls in shed_classes:
            self._reject(cls, "serve.rejected_brownout")
            raise BrownoutShed(
                f"class {cls!r} shed at brownout level L{level}; "
                f"retry after {retry_after_s:.1f}s",
                retry_after_s=retry_after_s,
            )
        admit, probe = self.breaker.allow()
        if not admit:
            self._reject(cls, "serve.rejected_breaker")
            raise BreakerOpen(
                f"circuit breaker open (cooldown {self.breaker._cooldown_s:.1f}s); failing fast"
            )
        deadline_s = deadline_ms / 1e3 if deadline_ms is not None else None
        if self._reject_unmeetable and deadline_s is not None:
            wait = self.predicted_wait_s(cls)
            if wait > deadline_s:
                if probe:
                    self.breaker.cancel_probe()  # probe slot not consumed
                self._reject(cls, "serve.rejected_deadline")
                raise DeadlineUnmeetable(
                    f"predicted wait {wait * 1e3:.1f}ms exceeds deadline {deadline_ms:.1f}ms"
                )
        model_cap = self._model_quota.get(model) if model is not None else None
        with self._lock:
            if self._in_queue[cls] >= self._quota[cls]:
                over_quota = "class"
            elif model_cap is not None and self._in_model.get(model, 0) >= model_cap:
                over_quota = "model"
            else:
                over_quota = None
                self._in_queue[cls] += 1
                if model is not None:
                    self._in_model[model] = self._in_model.get(model, 0) + 1
        if over_quota is not None:
            if probe:
                self.breaker.cancel_probe()
            if over_quota == "class":
                self._reject(cls, "serve.rejected_class_full")
                raise ClassQueueFull(
                    f"class {cls!r} at its weighted queue share ({self._quota[cls]})"
                )
            self._reject(cls, "serve.rejected_model_full")
            raise ModelQueueFull(
                f"model {model!r} at its in-system quota ({model_cap})"
            )
        pending = _Pending(cls, image, deadline_s, self._max_retries, probe, ctx, model=model)
        outer: Future = Future()
        try:
            inner = self._batcher.submit(
                image, deadline_ms=deadline_ms, priority=cls, ctx=ctx, model=model
            )
        except Exception:
            self._release(cls, model)
            if probe:
                self.breaker.cancel_probe()
            self._reject(cls, None)  # rejected_full already counted by the batcher
            raise
        self._reg.counter(f"serve.requests.{cls}").inc()
        if model is not None:
            self._reg.counter(f"serve.model_requests.{model}").inc()
        ctx.open_envelope()
        with self._lock:
            self._inflight_ctx[ctx.rid] = ctx
        inner.add_done_callback(lambda fut: self._on_done(pending, outer, fut))
        return outer

    def _reject(self, cls: str, cause_counter: str | None) -> None:
        self._reg.counter("serve.rejected").inc()
        self._reg.counter(f"serve.rejected.{cls}").inc()
        if cause_counter:
            self._reg.counter(cause_counter).inc()

    def _release(self, cls: str, model: str | None = None) -> None:
        with self._lock:
            self._in_queue[cls] = max(0, self._in_queue[cls] - 1)
            if model is not None and model in self._in_model:
                self._in_model[model] = max(0, self._in_model[model] - 1)

    # -- completion side (runs on batcher worker / timer threads) -----------

    def _resolve(self, pending: _Pending, outer: Future, value=None, exc: Exception | None = None) -> None:
        with self._lock:
            self._inflight_ctx.pop(pending.ctx.rid, None)
        pending.ctx.close_envelope()
        try:
            if exc is not None:
                outer.set_exception(exc)
            else:
                outer.set_result(value)
        except InvalidStateError:
            pass  # client cancelled; nothing left to deliver
        if self._heartbeat is not None:
            self._heartbeat()

    def _on_done(self, pending: _Pending, outer: Future, inner: Future) -> None:
        exc = inner.exception()
        if exc is None:
            self.breaker.on_success(pending.probe)
            latency_s = time.perf_counter() - pending.t_submit
            self._observe(pending.cls, latency_s)
            self._reg.counter(f"serve.completed.{pending.cls}").inc()
            if pending.model is not None:
                self._reg.histogram(
                    f"serve.model_latency_seconds.{pending.model}").observe(latency_s)
                self._reg.counter(f"serve.model_completed.{pending.model}").inc()
            self._release(pending.cls, pending.model)
            self._resolve(pending, outer, value=inner.result())
            return
        if isinstance(exc, (DeadlineExceeded, DrainTimeout)):
            # sheds are policy, not engine health: no breaker, no retry
            self._release(pending.cls, pending.model)
            self._resolve(pending, outer, exc=exc)
            return
        # engine failure: breaker accounting, then bounded retry
        self._reg.counter("serve.engine_failures").inc()
        self.breaker.on_failure(pending.probe)
        pending.probe = False  # the probe verdict is spent; a retry is ordinary traffic
        with self._lock:
            retries_enabled = self._retries_enabled
        if pending.retries_left <= 0 or not retries_enabled or self.breaker.state == BREAKER_OPEN or (
            pending.t_deadline is not None and time.perf_counter() >= pending.t_deadline
        ):
            self._release(pending.cls, pending.model)
            self._resolve(pending, outer, exc=exc)
            return
        pending.retries_left -= 1
        pending.attempt += 1
        delay = self._backoff_s * (2 ** (pending.attempt - 1))
        delay *= 1.0 + self._jitter * self._rng.uniform(-1.0, 1.0)
        self._reg.counter("serve.retries").inc()
        self._reg.counter(f"serve.retries.{pending.cls}").inc()
        pending.ctx.phase = "retrying"  # re-enters "queued" on the retry submit
        timer = threading.Timer(max(delay, 0.0), self._retry, args=(pending, outer, exc))
        timer.daemon = True
        timer.start()

    def _retry(self, pending: _Pending, outer: Future, prev_exc: Exception) -> None:
        if pending.t_deadline is not None and time.perf_counter() >= pending.t_deadline:
            self._release(pending.cls, pending.model)
            self._resolve(pending, outer, exc=DeadlineExceeded("deadline passed during retry backoff"))
            return
        if self.breaker.state == BREAKER_OPEN:
            self._release(pending.cls, pending.model)
            self._resolve(pending, outer, exc=prev_exc)
            return
        remaining_ms = (
            None if pending.t_deadline is None
            else max((pending.t_deadline - time.perf_counter()) * 1e3, 0.0)
        )
        try:
            inner = self._batcher.submit(
                pending.image, deadline_ms=remaining_ms, priority=pending.cls,
                ctx=pending.ctx, model=pending.model,
            )
        except Exception as e:  # noqa: BLE001 — stopped batcher / QueueFull: final answer
            self._release(pending.cls, pending.model)
            self._resolve(pending, outer, exc=e)
            return
        inner.add_done_callback(lambda fut: self._on_done(pending, outer, fut))

    # -- introspection (healthz / hang reports) ------------------------------

    def oldest_inflight(self) -> dict | None:
        """The oldest in-system request's {id, class, deadline_ms, age_s,
        phase} — the "whose request is wedged" line in hang reports and
        /varz. None when the system is idle."""
        with self._lock:
            if not self._inflight_ctx:
                return None
            oldest = min(self._inflight_ctx.values(), key=lambda c: c.t_arrival)
        return oldest.as_dict()

    def state(self) -> dict:
        """JSON-safe snapshot: breaker, per-class occupancy/quota, predictor."""
        with self._lock:
            in_queue = dict(self._in_queue)
            in_model = dict(self._in_model)
            ewma = self._ewma_s
            brownout = {
                "level": self._brownout_level,
                "shed_classes": sorted(self._shed_classes),
                "deadline_margin": self._deadline_margin,
                "retries_enabled": self._retries_enabled,
            }
        return {
            "breaker": self.breaker.state_name,
            "breaker_state": self.breaker.state,
            "brownout": brownout,
            "ewma_latency_s": ewma,
            "predictor": self._predictor,
            "predicted_wait_s": self.predicted_wait_s(),
            "oldest_request": self.oldest_inflight(),
            "queued_total": sum(in_queue.values()),
            "classes": {
                cls: {
                    "in_queue": in_queue[cls],
                    "quota": self._quota[cls],
                    "weight": self._weights[cls],
                }
                for cls in CLASSES
            },
            "models": None if self._models is None else {
                m: {
                    "in_system": in_model.get(m, 0),
                    "quota": self._model_quota.get(m),
                    "default": m == self._default_model,
                }
                for m in self._models
            },
        }

    @classmethod
    def from_config(cls, batcher, ac, *, heartbeat=None, seed: int = 0,
                    models=None, default_model: str | None = None,
                    model_quotas=None) -> "AdmissionController":
        """Build from a config.AdmissionConfig block (cli/serve.py); the zoo
        kwargs ride alongside from the serve.zoo block (serve/zoo.py)."""
        return cls(
            batcher,
            weights=tuple(ac.weights),
            default_class=ac.default_class,
            max_retries=ac.max_retries,
            retry_backoff_ms=ac.retry_backoff_ms,
            retry_jitter=ac.retry_jitter,
            breaker_threshold=ac.breaker_threshold,
            breaker_cooldown_s=ac.breaker_cooldown_s,
            ewma_alpha=ac.ewma_alpha,
            reject_unmeetable=ac.reject_unmeetable,
            predictor=ac.predictor,
            predictor_quantile=ac.predictor_quantile,
            seed=seed,
            heartbeat=heartbeat,
            models=models,
            default_model=default_model,
            model_quotas=model_quotas,
        )
