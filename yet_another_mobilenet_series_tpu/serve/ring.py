"""Device-resident request ring: one dispatch per steady-state window.

PR 11's back-to-back runs removed the completion WAKE-UP between batches on
a saturated bucket, but every batch is still its own XLA dispatch — the
host↔device boundary is paid once per batch forever. PAPERS.md "Kernel
Looping" (arXiv 2410.23668) names the end state: inter-call
synchronization, not compute, caps steady-state inference throughput, so a
saturated window should be ONE device program. The ring is that program.

**Shape.** A ring of R pre-staged batch slots per hot ``(model, bucket,
image_size)`` key — R is ``serve.ring.slots``, the bucket is always the
engine's biggest (a saturated window has no reason to ride a smaller one).
Host threads only FEED slots: each slot is a ``(bucket, S, S, 3)`` host
buffer in the wire dtype (u8 or f32), transferred with async
``jax.device_put`` through the same fence-tracked slot-pool idiom as
overlapped staging (serve/engine.py ``_SlotPool``), so the H2D copy of slot
k+1 overlaps the staging of slot k+2 and the compute of window N-1. One
AOT-compiled executable then consumes ALL currently-staged slots in a
single dispatch: a ``lax.scan`` over the stacked slot axis runs the same
per-chunk folded forward the K=1 executables compile — R iterations, one
host→device boundary, one ``serve.dispatch_seconds`` observation.

**The mask.** The scan carries an active-slot mask so a partially-filled
window (staged < R) runs the SAME executable — no per-fill recompile, no
shape cliff. Padded slots enter as device-side zero buffers (no H2D) and
their outputs are selected away by the mask; active slots' logits pass
through a scalar-bool ``where`` untouched, so ring logits are **bitwise
identical** to the per-batch path by construction — the same discipline as
the fused-K scan, pinned by tests/test_ring.py across buckets, sizes, the
u8 wire, int8 weights, and multi-model zoos.

**Feed/drain lifecycle.** The pipeline (serve/pipeline.py) engages the ring
only when the queue holds at least ``min_slots(R, serve.ring.min_fill)``
slots' worth of same-(model, shape) traffic — a saturated window — and
falls back to the existing per-batch dispatch otherwise (sync / pipelined /
fused / overlapped modes are intact and A/B-able). Within a window every
slot but the LAST is full, so the valid rows of the scan's ``(R, bucket,
classes)`` output are contiguous after flattening and the standard
:class:`~.engine.PendingPrediction` drains the whole window with one
device_get. Slot host buffers are rewritable only after the consuming ring
dispatch's OUTPUT logits exist (the fence; donation deletes the inputs), so
feeds for window N+1 can never tear a transfer still in flight for N.

This module holds the host-side window bookkeeping; the executables, the
staging pools, and the dispatch itself live on the engine
(:meth:`~.engine.InferenceEngine.ring_stage` /
:meth:`~.engine.InferenceEngine.ring_dispatch`).
"""

from __future__ import annotations

import math


class RingEntry:
    """One staged (fed) ring slot, pending its window's dispatch.

    ``x`` is the device array the async ``device_put`` returned (possibly
    still in transfer — only the compiled program may consume it, and it is
    donated there), ``rows`` the real rows staged into it (the rest is
    zero pad), ``slot`` the engine staging-pool slot backing the host
    buffer (None for an exact-fill zero-copy feed) whose fence the ring
    dispatch arms."""

    __slots__ = ("x", "rows", "slot")

    def __init__(self, x, rows: int, slot=None):
        self.x = x
        self.rows = int(rows)
        self.slot = slot


def min_slots(ring_slots: int, min_fill: float) -> int:
    """Staged slots a window must reach before a ring dispatch commits.

    ``serve.ring.min_fill`` is a fraction of the ring depth; below it the
    mask would discard more compute than the saved dispatch boundaries are
    worth, so the pipeline rides the per-batch path instead. Always at
    least 1 (an enabled ring with a tiny min_fill still needs one slot)."""
    return max(1, math.ceil(ring_slots * min_fill - 1e-9))


def window_chunks(items, cap: int, max_slots: int):
    """Split ``items`` into at most ``max_slots`` contiguous chunks of at
    most ``cap`` each — the window's slot plan. Returns ``(chunks,
    leftover)``: only the last chunk may be partial (the contiguity the
    drain's single flatten-and-slice relies on), and ``leftover`` holds
    whatever did not fit this window (it rides the next one, or the
    per-batch path)."""
    if cap < 1 or max_slots < 1:
        raise ValueError(f"window needs cap >= 1 and max_slots >= 1, got {cap}, {max_slots}")
    chunks = []
    start = 0
    while start < len(items) and len(chunks) < max_slots:
        chunks.append(items[start : start + cap])
        start += cap
    return chunks, items[start:]
