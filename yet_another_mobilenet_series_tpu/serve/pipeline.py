"""Pipelined continuous batching: collect/dispatch and completion decoupled.

The plain :class:`~.batcher.MicroBatcher` is a one-thread cycle — collect,
predict (which blocks on the device_get), resolve futures, repeat — so the
host's collect/pad/stage work and the device's compute strictly alternate:
while the chip runs a bucket, no requests coalesce, and while the host
coalesces, the chip idles. BENCH_SERVE_r01 shows the cost (the batch-32
bucket delivering LOWER QPS than batch-8 on CPU rehearsal).

:class:`PipelinedBatcher` splits the cycle across two threads around the
engine's async dispatch (serve/engine.py ``predict_async``):

- the **collect thread** gathers a batch, stages + dispatches it via
  ``predict_async`` (no sync — JAX async dispatch returns as soon as the
  work is enqueued on the device), and pushes the resulting
  :class:`~.engine.PendingPrediction` into a bounded in-flight window;
- the **completion thread** pops handles in dispatch order, blocks on
  ``result()`` (the only host<->device sync), re-checks deadlines, and
  resolves the futures.

So the NEXT bucket fills and stages while the PREVIOUS one executes on the
device — continuous batching. While the window is full the collect thread
keeps TOPPING UP the batch in hand instead of closing it early: dispatch
cannot proceed anyway, and a partial bucket pads with dead rows the device
then computes — under saturation every dispatched bucket arrives full. A
topped-up batch larger than the engine's biggest bucket still dispatches
as ONE ``predict_async`` call (one window slot per size group): the engine
serves it through the fused multi-chunk executables
(``serve.fuse_chunks``, one lax.scan dispatch per ladder piece), so
saturation-driven top-up composes with fusion instead of degrading into a
per-chunk host loop.
``max_inflight`` bounds the number of dispatched-but-unsynced batches, and
the slot is reserved BEFORE dispatch, so at most ``max_inflight``
executions are ever enqueued device-side:
``1`` = classic double buffering (stage batch k+1 while k computes; never
two concurrent executions — the right setting when host and "device" share
cores, i.e. CPU), ``2`` (default) additionally keeps one execution queued
behind the running one so the device never drains between batches. A full
window blocks the collect thread, which backs pressure up into the bounded
submit queue and ultimately :class:`~.batcher.QueueFull`, exactly like the
sync path.

**Back-to-back dispatch** (``run_max`` > 1, serve.overlap config) is the
device-resident steady state for a SATURATED bucket: after dispatching a
batch, while the queue already holds a full next batch, a window slot is
free without blocking, and the run has room, the collect thread drains and
dispatches the next batch immediately — no linger, no completion wake-up in
between — and hands the whole run to the completion thread as ONE item. The
completion thread then syncs only the run's TAIL (device execution is FIFO:
the tail's logits existing proves every earlier batch completed, so their
``result()`` calls are pure device_get, zero further blocking syncs) inside
a ``serve/resident`` span. Each wake-up observes
``serve.dispatches_per_wakeup`` — ENGINE dispatch pieces per completion
wake-up (``handle.dispatches``: an oversized batch a non-fused engine
serves as several pieces counts them all, same granularity as
``serve.dispatch_seconds``). On a fused engine every saturated batch is one
piece, so a mean > 1 on a saturated bucket means runs really formed — the
structural claim the r05 bench artifact pins — and
paired with the engine's overlapped staging (fence-tracked slot pool +
async ``jax.device_put``) the H2D transfer of batch N+1 overlaps compute of
batch N, so steady-state ``serve.achieved_flops_per_s`` approaches the
single-dispatch number. Any blocking window acquire FLUSHES the pending run
first — a run the completion thread has not been handed yet can never be
the thing its window slots are waiting on (the deadlock this ordering rule
exists to make impossible).

**Ring feed/drain** (``serve.ring.enable``, serve/ring.py) replaces
back-to-back dispatch on a saturated bucket with something strictly
stronger: instead of N dispatches per completion wake-up, the collect
thread FEEDS up to R max-bucket slots (engine ``ring_stage`` — async H2D
per slot, no dispatch) and commits the whole window as ONE masked-scan
dispatch (``ring_dispatch``). Engagement is conservative: the queue (plus
the batch in hand) must hold at least ``min_slots(R, min_fill)`` slots'
worth of rows, and only the largest same-(model, shape) group rides the
ring — everything else (mixed sizes, shallow queues, off-ladder sizes,
ring-less engines) falls back to the existing per-batch path unchanged,
so sync / pipelined / fused / overlapped semantics stay intact and
A/B-able. A ring window occupies ONE in-flight window slot and counts as
ONE engine piece in ``serve.dispatches_per_wakeup`` (the whole point:
dispatches-per-window drops to 1/R at full fill).

Failure semantics are preserved, not weakened:

- ``QueueFull`` backpressure and dispatch-time deadline shedding behave as
  in the sync batcher (shared code), and so does brownout fill-or-flush
  (serve/brownout.py L2+): the shared ``_linger_fill`` collapses its linger
  window to zero, which this batcher's top-up and short-drain paths inherit
  — under a storm the queue supplies full batches without the wait;
- deadlines are ALSO checked at completion: a request whose deadline passed
  while its batch was executing gets :class:`~.batcher.DeadlineExceeded`
  instead of a stale answer (``serve.shed_at_completion`` counts these,
  on top of the shared ``serve.shed_deadline``);
- an engine failure at dispatch or at sync fails exactly that batch's
  futures and both threads keep serving;
- ``stop(drain=True)`` drains the request queue, then the in-flight window,
  in FIFO order — BOUNDED by ``drain_timeout_s``: a completion thread
  wedged inside a hung ``result()`` cannot hang shutdown; the remaining
  futures fail with :class:`~.batcher.DrainTimeout` and the wedged daemon
  threads are abandoned (their late answers are dropped by the idempotent
  resolution helpers);
- both loops carry top-level exception guards (yamt-lint YAMT011): an
  unexpected crash fails every live future, counts
  ``serve.thread_crashes``, and — for the collect thread — still delivers
  the drain sentinel so the completion thread exits too.

Instrumentation (obs/): ``serve.inflight`` gauge (window occupancy at each
push/pop) plus everything the engine and shared batcher record —
``serve.dispatch_seconds``, ``serve.dispatch_to_complete_seconds``,
``serve.batch_size``, ``serve.queue_wait_seconds``.
"""

from __future__ import annotations

import inspect
import queue
import threading
import time

import numpy as np

from ..obs import trace as obs_trace
from . import ring as ring_lib
from .batcher import _STOP, DeadlineExceeded, MicroBatcher, _Request, _group_by_shape

# in-flight window sentinel: collect thread -> completion thread shutdown
_DRAINED = object()


class PipelinedBatcher(MicroBatcher):
    """Two-thread continuous batcher over an engine with ``predict_async``.

    ``engine`` needs ``predict_async(images) -> handle`` with a blocking
    ``handle.result()`` — the :class:`~.engine.InferenceEngine` protocol.
    Everything client-facing (``submit`` / ``QueueFull`` / deadlines /
    ``stop``) matches :class:`~.batcher.MicroBatcher`.
    """

    def __init__(
        self,
        engine,
        *,
        max_inflight: int = 2,
        run_max: int = 1,
        max_batch: int = 32,
        max_wait_ms: float = 2.0,
        queue_depth: int = 256,
        default_deadline_ms: float = 0.0,
        drain_timeout_s: float = 0.0,
        wire_dtype=None,
        ring_min_fill: float = 0.5,
    ):
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        if run_max < 1:
            raise ValueError(f"run_max must be >= 1, got {run_max}")
        if not 0.0 < ring_min_fill <= 1.0:
            raise ValueError(f"ring_min_fill must be in (0, 1], got {ring_min_fill}")
        # the wire dtype rides the engine (serve.quant.wire): submit-side
        # coercion must match the engine's staging buffers, so inherit it
        # unless the caller overrides (bare test doubles default to f32)
        if wire_dtype is None:
            wire_dtype = getattr(engine, "wire_np_dtype", np.float32)
        super().__init__(
            engine.predict,
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            queue_depth=queue_depth,
            default_deadline_ms=default_deadline_ms,
            drain_timeout_s=drain_timeout_s,
            wire_dtype=wire_dtype,
        )
        self._engine = engine
        self._max_inflight = max_inflight
        # back-to-back run cap: > 1 lets a saturated bucket dispatch up to
        # this many batches per completion wake-up (bounded by the window,
        # which stays the device-side memory bound); 1 = legacy per-batch
        self._run_max = int(run_max)
        # thread request identity into the engine when it speaks the ctxs
        # extension (InferenceEngine/FaultyEngine do; bare test doubles with
        # predict_async(images) keep working — the batcher's own phase
        # advances cover them)
        try:
            params = inspect.signature(engine.predict_async).parameters
            self._engine_takes_ctxs = "ctxs" in params
            # zoo-aware engines additionally take model= (serve/zoo.py);
            # groups are (model, shape)-pure so one kwarg per dispatch works
            self._engine_takes_model = "model" in params
        except (TypeError, ValueError):
            self._engine_takes_ctxs = False
            self._engine_takes_model = False
        # ring feed/drain mode (serve/ring.py): engaged iff the engine was
        # built with ring_slots > 0 (serve.ring.enable); _ring_min_slots is
        # the engagement threshold in STAGED SLOTS (min_fill x R, ceil)
        self._ring_slots = int(getattr(engine, "ring_slots", 0) or 0)
        self._ring_min_slots = (
            ring_lib.min_slots(self._ring_slots, ring_min_fill) if self._ring_slots else 0)
        self._ring_cap = int(engine.buckets[-1]) if self._ring_slots else 0
        # dispatched-but-unsynced budget, acquired BEFORE each dispatch so
        # at most max_inflight executions are ever enqueued device-side
        self._window = threading.BoundedSemaphore(max_inflight)
        # runs of (handle, live_requests) pairs in dispatch order; the
        # semaphore is the bound, the queue just carries them to the
        # completion thread (a run_max=1 run is a singleton list)
        self._inflight: queue.Queue = queue.Queue()
        self._inflight_n = 0
        self._inflight_lock = threading.Lock()
        self._completion: threading.Thread | None = None

    def _inflight_adj(self, delta: int) -> None:
        with self._inflight_lock:
            self._inflight_n += delta
            self._reg.gauge("serve.inflight").set(self._inflight_n)

    def inflight(self) -> int:
        """Dispatched-but-unsynced batches right now (health/hang reports)."""
        with self._inflight_lock:
            return self._inflight_n

    def worker_threads(self) -> list[dict]:
        """Name/liveness of the batcher's worker threads — the serving
        section of the watchdog's hang report (obs/watchdog.py)."""
        return [
            {"name": t.name, "alive": t.is_alive()}
            for t in (self._thread, self._completion)
            if t is not None
        ]

    # -- lifecycle (two threads) --------------------------------------------

    def _start_threads(self) -> None:
        self._thread = threading.Thread(target=self._collect_loop, name="serve-collect", daemon=True)  # yamt-lint: disable=YAMT019 — lifecycle: threads start before any client can submit; submit's None-check is the not-started guard
        self._completion = threading.Thread(target=self._complete_loop, name="serve-complete", daemon=True)
        self._thread.start()
        self._completion.start()

    def _join_threads(self, timeout_s: float | None = None) -> bool:
        # one shared drain budget across both joins, not one budget each
        deadline = None if timeout_s is None else time.perf_counter() + timeout_s
        self._thread.join(timeout_s)  # pushes _DRAINED into the in-flight queue on exit
        if deadline is not None:
            timeout_s = max(0.0, deadline - time.perf_counter())
        self._completion.join(timeout_s)
        drained = not (self._thread.is_alive() or self._completion.is_alive())
        if drained:
            self._completion = None
        return drained

    # -- collect/dispatch thread --------------------------------------------

    def _collect_loop(self) -> None:
        try:
            obs_trace.get_tracer().register_thread()  # "serve-collect" Perfetto row
            self._collect_loop_inner()
        except Exception as e:  # noqa: BLE001 — terminal: contain, don't hang clients
            self._thread_crash(e)
        finally:
            self._inflight.put(_DRAINED)

    def _collect_loop_inner(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            if not batch:
                self._idle_wakeups += 1
                continue
            self._dispatch_batch(batch)
            if self._exit_after_batch:
                return

    def _acquire_window_topping_up(self, batch: list[_Request]) -> None:
        """Block until a window slot frees, topping the batch up from the
        request queue meanwhile. While the window is full nothing can
        dispatch anyway, so closing a partial batch early would only pad a
        bucket with dead rows — fill matters more than a head start (the
        serve_bench fill counters showed exactly this: partial pipelined
        buckets burning padded compute)."""
        while not self._window.acquire(blocking=False):
            if self._exit_after_batch or len(batch) >= self._max_batch:
                self._window.acquire()
                return
            try:
                nxt = self._q.get(timeout=0.005)
            except queue.Empty:
                continue
            if nxt is _STOP:
                self._exit_after_batch = True
            else:
                batch.append(nxt)

    def _dispatch_batch(self, batch: list[_Request]) -> None:
        # ring feed/drain first (serve.ring.enable): a saturated window
        # rides ONE masked-scan dispatch; on False the batch is untouched
        # (possibly topped up) and falls through to the per-batch path
        if self._ring_min_slots and self._ring_try(batch):
            return
        # reserve the slot (window = dispatched-but-unsynced cap) BEFORE
        # dispatch — backpressure toward submit(); released by completion
        self._acquire_window_topping_up(batch)
        run: list[tuple] = []
        self._dispatch_groups(batch, run)
        # back-to-back extension: while the bucket stays saturated (a FULL
        # next batch is already queued — no linger would improve its fill),
        # a window slot is free WITHOUT blocking, and the run has room,
        # dispatch the next batch with no completion wake-up in between.
        # The completion thread receives the whole run as one item and
        # syncs only its tail.
        while (
            run
            and len(run) < self._run_max
            and not self._exit_after_batch
            and self._q.qsize() >= self._max_batch
        ):
            if not self._window.acquire(blocking=False):
                break  # window full: the run is as deep as the device bound allows
            nxt = self._drain_full_batch_nowait()
            if not nxt:
                self._window.release()
                break
            if len(nxt) < self._max_batch and not self._exit_after_batch:
                # short drain: the qsize saturation signal overstated what
                # was really queued (it counts the stop sentinel, and a
                # concurrent stop() sweep can race the drain) — this batch
                # is NOT saturated, so fill it through the normal lingering
                # path instead of dispatching a padded partial bucket with
                # zero linger. (When the sentinel was drawn we are exiting:
                # dispatch what we have, lingering would only delay drain.)
                self._linger_fill(nxt)
            self._dispatch_groups(nxt, run)
        self._flush_run(run)

    # -- ring feed/drain (serve/ring.py) ------------------------------------

    def _ring_try(self, batch: list[_Request]) -> bool:
        """Serve ``batch`` as a device-resident ring window when it is
        worth one: the batch plus the queue must hold at least
        ``min_slots`` slots' worth of rows (the min_fill engagement
        condition), and the window is the largest same-(model, shape)
        group whose size is ring-ready (on the tenant's warmed ladder).
        Returns True when the batch was fully handled — the ring group as
        ONE feed+dispatch, every other group through the normal per-batch
        machinery. Returns False with the batch intact (possibly topped
        up from the queue, which the per-batch path would have drained
        anyway) when no window can form — shallow queue, mixed traffic,
        off-ladder sizes — so the existing path serves it unchanged."""
        cap, r = self._ring_cap, self._ring_slots
        if len(batch) + self._q.qsize() < self._ring_min_slots * cap:
            return False
        # saturation top-up with NO linger, to at most one full window:
        # the queue reported the rows already there
        while len(batch) < r * cap and not self._exit_after_batch:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is _STOP:
                self._exit_after_batch = True
            else:
                batch.append(nxt)
        live = self._shed_expired(batch)
        batch[:] = live
        if not live:
            return True  # everything shed: nothing to dispatch, no window taken
        groups = _group_by_shape(live)
        best = -1
        for i, g in enumerate(groups):
            if (
                len(g) > (self._ring_min_slots - 1) * cap
                and self._engine.ring_ready(g[0].model, g[0].image.shape[0])
                and (best < 0 or len(g) > len(groups[best]))
            ):
                best = i
        if best < 0:
            return False  # no ring-worthy group; per-batch path serves the batch
        ring_group = groups.pop(best)
        batch.clear()
        # ONE window slot for the whole ring window (it is one handle, one
        # dispatch); no run is pending yet, so a blocking acquire is safe
        self._window.acquire()
        self._ring_dispatch_group(ring_group)
        rest = [req for g in groups for req in g]
        if rest:
            # leftover groups ride the normal path — acquired AFTER the
            # ring run was flushed, honoring the flush-before-blocking-
            # acquire ordering rule
            self._acquire_window_topping_up(rest)
            run: list[tuple] = []
            self._dispatch_groups(rest, run)
            self._flush_run(run)
        return True

    def _ring_dispatch_group(self, group: list[_Request]) -> None:
        """Feed one (model, shape)-pure group into ring slots and commit
        the window: per-slot ``ring_stage`` (async H2D, no dispatch) then
        ONE ``ring_dispatch``. The caller holds the window slot; an engine
        failure releases it and fails exactly this group's futures — both
        threads keep serving, same policy as ``_dispatch_groups``."""
        self._reg.histogram("serve.batch_size").observe(len(group))
        for req in group:
            req._advance("dispatched")
        try:
            chunks, leftover = ring_lib.window_chunks(group, self._ring_cap, self._ring_slots)
            assert not leftover  # _ring_try caps the drain at r * cap rows
            entries = [
                self._engine.ring_stage(np.stack([r.image for r in chunk]))
                for chunk in chunks
            ]
            handle = self._engine.ring_dispatch(
                entries,
                ctxs=[r.ctx for r in group if r.ctx is not None],
                model=group[0].model,
            )
        except Exception as e:  # noqa: BLE001 — a dying engine must not hang clients
            self._window.release()
            for req in group:
                self._finish_err(req, e)
            return
        self._inflight_adj(+1)
        self._inflight.put([(handle, group)])

    def _drain_full_batch_nowait(self) -> list[_Request]:
        """Up to max_batch queued requests with NO lingering — only called
        when the queue reported a full batch available (saturation). The
        stop sentinel sets ``_exit_after_batch`` exactly like ``_collect``;
        anything enqueued after it is failed by stop()'s final sweep."""
        batch: list[_Request] = []
        while len(batch) < self._max_batch:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is _STOP:
                self._exit_after_batch = True
                break
            batch.append(nxt)
        return batch

    def _dispatch_groups(self, batch: list[_Request], run: list[tuple]) -> None:
        """Shed, partition by image shape, dispatch each group, append the
        ``(handle, group)`` pairs to ``run``. The caller holds ONE window
        slot for the first group; mixed-size groups past the first acquire
        their own — FLUSHING the pending run first, so the blocking acquire
        can never wait on window slots held by a run the completion thread
        has not been handed yet."""
        live = self._shed_expired(batch)
        if not live:
            self._window.release()
            return
        for i, group in enumerate(_group_by_shape(live)):
            if i:
                self._flush_run(run)
                self._window.acquire()
            self._reg.histogram("serve.batch_size").observe(len(group))
            for req in group:  # queued -> in-flight edge, collect thread
                req._advance("dispatched")
            try:
                stacked = np.stack([r.image for r in group])
                kwargs = {}
                if self._engine_takes_ctxs:
                    kwargs["ctxs"] = [r.ctx for r in group if r.ctx is not None]
                if self._engine_takes_model and group[0].model is not None:
                    kwargs["model"] = group[0].model
                handle = self._engine.predict_async(stacked, **kwargs)
            except Exception as e:  # noqa: BLE001 — a dying engine must not hang clients
                self._window.release()
                for req in group:
                    self._finish_err(req, e)
                continue
            run.append((handle, group))
            self._inflight_adj(+1)

    def _flush_run(self, run: list[tuple]) -> None:
        """Hand the accumulated run to the completion thread as ONE item."""
        if run:
            self._inflight.put(list(run))
            run.clear()

    # -- completion thread --------------------------------------------------

    def _complete_loop(self) -> None:
        try:
            obs_trace.get_tracer().register_thread()  # "serve-complete" Perfetto row
            self._complete_loop_inner()
        except Exception as e:  # noqa: BLE001 — terminal: contain, don't hang clients
            self._thread_crash(e)

    def _complete_loop_inner(self) -> None:
        tracer = obs_trace.get_tracer()
        while True:
            item = self._inflight.get()
            if item is _DRAINED:
                return
            run = item
            # engine dispatches the collect thread managed per completion
            # wake-up: the back-to-back instrument. Counts real dispatch
            # PIECES (handle.dispatches — an oversized batch on a non-fused
            # engine is one handle but several pieces), matching the
            # serve.dispatch_seconds granularity; bare test doubles without
            # the attribute count as one dispatch.
            self._reg.histogram("serve.dispatches_per_wakeup").observe(
                sum(getattr(h, "dispatches", 1) for h, _ in run))
            if len(run) > 1:
                # device-resident run: sync ONLY the tail. Execution is FIFO
                # on the device, so the tail's logits existing proves every
                # earlier batch in the run completed — their result() calls
                # below are pure device_get, no further blocking sync.
                with tracer.span("serve/resident", "serve", batches=len(run)):
                    try:
                        run[-1][0].result()
                    except Exception:  # yamt-lint: disable=YAMT012 — ordering optimization only; the per-batch result() below re-raises and fails exactly that batch
                        pass
            for handle, live in run:
                self._complete_one(handle, live)

    def _complete_one(self, handle, live: list[_Request]) -> None:
        try:
            logits = handle.result()
        except Exception as e:  # noqa: BLE001 — fail this batch, keep draining
            self._inflight_adj(-1)
            self._window.release()
            for req in live:
                self._finish_err(req, e)
            return
        # the device is free the moment the sync returns: open the
        # window before the host-side future resolution
        self._inflight_adj(-1)
        self._window.release()
        now = time.perf_counter()
        done = 0
        for req, row in zip(live, logits):
            if req.t_deadline is not None and now > req.t_deadline:
                # expired while the batch executed: a stale answer is a
                # shed, not a success (completion-time deadline check)
                self._reg.counter("serve.shed_at_completion").inc()
                self._shed(req, DeadlineExceeded(
                    f"completed {now - req.t_enqueue:.3f}s past deadline"
                ))
            else:
                done += self._finish_ok(req, row)
        if done:
            self._reg.counter("serve.completed").inc(done)
