"""Deterministic, seeded fault injection around the inference engine.

Every recovery path in the resilience edge — bounded retry, circuit breaker,
completion-time shedding, drain timeout, watchdog hang reports — is dead
code until something actually fails, and "unplug the TPU" is not a unit
test. (The WIRE-level twin of this module is serve/netchaos.py: where
FaultyEngine injects at the engine edge inside one process, the netchaos
proxy injects between processes — blackholes, resets, half-open sockets —
the failure class only a multi-host fleet ever sees.) :class:`FaultyEngine` wraps anything speaking the engine protocol
(``predict_async(images) -> handle``, ``handle.result()``, ``predict``) and
injects failures on a SEEDED schedule, so every chaos scenario in
tests/test_fault_injection.py and the serve_bench chaos A/B is exactly
reproducible:

- **failure rate** — ``failure_rate`` is PER REQUEST ROW: a dispatch of
  ``n`` rows fails with probability ``1 - (1 - rate)**n`` (one
  ``random.Random(seed)`` draw per dispatch, deterministic in dispatch
  order — the batcher's collect thread serializes dispatches). Per-row
  compounding keeps a "5% fault rate" meaning 5% of REQUESTS affected
  regardless of how the batcher coalesces them — a flat per-dispatch rate
  would make heavy coalescing silently hide the chaos;
- **fail-N-then-recover** — the first ``fail_first_n`` dispatches fail, the
  rest succeed: the breaker drill (streak opens it, the half-open probe
  lands after recovery and closes it);
- **added latency** — ``latency_s`` of sleep with per-row probability
  ``latency_rate`` (compounded like failures), applied inside ``result()``
  (the completion thread's sync), never at dispatch — the device-feeding
  path stays non-blocking exactly as in a real slow-device episode (Kernel
  Looping discipline). ``latency_after_n`` delays the onset: the first N
  dispatches run clean, then the injection begins — a replica that
  DEGRADES mid-run (the gray-failure drill: the router must notice a
  replica that was healthy when it learned its baseline);
- **hang-until-event** — dispatch index ``hang_at`` blocks its ``result()``
  on :attr:`hang_release` indefinitely: the drain-timeout / stall-watchdog
  drill. Setting the event un-wedges the handle, which then serves the
  batch for real (recovery, not just release).

``fail_at`` picks where failures surface: ``"dispatch"`` raises out of
``predict_async`` (collect thread), ``"result"`` returns a handle that
raises at sync (completion thread) — the two failure edges the pipelined
batcher must contain independently.

Injected events are counted (``serve.faults.failures`` / ``.delays`` /
``.hangs``) so a chaos round's accounting is auditable from the same obs
registry snapshot as the recovery metrics it provoked. Attribute access
falls through to the wrapped engine (``buckets``, ``image_sizes``, ...), so
the wrapper is drop-in anywhere an engine goes.
"""

from __future__ import annotations

import inspect
import random
import threading
import time

from ..obs.registry import get_registry


class InjectedFault(RuntimeError):
    """A deterministic injected engine failure (serve/faults.py) — the
    'transient engine error' every recovery path trains against."""


class _FaultyHandle:
    """Wraps (or replaces) a pending handle: applies the injected delay /
    failure / hang at ``result()`` time, on the completion thread."""

    __slots__ = ("_fe", "_images", "_inner", "_delay_s", "_fail", "_hang")

    def __init__(self, fe, images, inner, delay_s, fail, hang):
        self._fe = fe
        self._images = images
        self._inner = inner
        self._delay_s = delay_s
        self._fail = fail
        self._hang = hang

    def result(self):
        if self._hang:
            # a real wedge: blocks until the operator (test) releases it,
            # then serves the batch for real — hang, then recovery
            self._fe.hang_release.wait()
            return self._fe._engine.predict(self._images)
        if self._delay_s > 0:
            time.sleep(self._delay_s)
        if self._fail:
            raise InjectedFault(f"injected result failure (dispatch #{self._fail - 1})")
        return self._inner.result()


class FaultyEngine:
    """Engine-protocol wrapper with a seeded fault schedule. See module
    docstring for the knobs; ``hang_release`` is the un-wedge event."""

    def __init__(
        self,
        engine,
        *,
        seed: int = 0,
        failure_rate: float = 0.0,
        fail_first_n: int = 0,
        fail_at: str = "dispatch",
        latency_s: float = 0.0,
        latency_rate: float = 1.0,
        latency_after_n: int = 0,
        hang_at: int | None = None,
    ):
        if fail_at not in ("dispatch", "result"):
            raise ValueError(f"fail_at must be 'dispatch' or 'result', got {fail_at!r}")
        self._engine = engine
        self._failure_rate = failure_rate
        self._fail_first_n = fail_first_n
        self._fail_at = fail_at
        self._latency_s = latency_s
        self._latency_rate = latency_rate
        self._latency_after_n = max(0, int(latency_after_n))
        self._hang_at = hang_at
        self.hang_release = threading.Event()
        self._rng = random.Random(seed)
        self._idx = 0
        self._lock = threading.Lock()
        self._reg = get_registry()
        # forward request contexts (serve/context.py) only when the wrapped
        # engine speaks the extension — test doubles with predict_async(images)
        # stay drop-in
        try:
            self._takes_ctxs = "ctxs" in inspect.signature(engine.predict_async).parameters
        except (TypeError, ValueError):
            self._takes_ctxs = False

    def _decide(self, n_rows: int) -> tuple[int, bool, float, bool]:
        """(dispatch index, fail?, delay_s, hang?) — one locked draw pair per
        dispatch so the schedule is deterministic in dispatch order. Rates
        compound per row: p_dispatch = 1 - (1 - rate)**n_rows."""
        with self._lock:
            idx = self._idx
            self._idx += 1
            fail = idx < self._fail_first_n or (
                self._failure_rate > 0
                and self._rng.random() < 1.0 - (1.0 - self._failure_rate) ** n_rows
            )
            delay = (
                self._latency_s
                if self._latency_s > 0
                and idx >= self._latency_after_n  # degrade-onset gate
                and self._rng.random() < 1.0 - (1.0 - self._latency_rate) ** n_rows
                else 0.0
            )
            hang = self._hang_at is not None and idx == self._hang_at
        return idx, fail, delay, hang

    def predict_async(self, images, ctxs=None):
        idx, fail, delay, hang = self._decide(int(images.shape[0]))
        if hang:
            self._reg.counter("serve.faults.hangs").inc()
            return _FaultyHandle(self, images, None, 0.0, 0, hang=True)
        if fail:
            self._reg.counter("serve.faults.failures").inc()
            if self._fail_at == "dispatch":
                raise InjectedFault(f"injected dispatch failure (dispatch #{idx})")
            return _FaultyHandle(self, images, None, delay, idx + 1, hang=False)
        if delay > 0:
            self._reg.counter("serve.faults.delays").inc()
        if self._takes_ctxs:
            inner = self._engine.predict_async(images, ctxs=ctxs)
        else:
            inner = self._engine.predict_async(images)
        return _FaultyHandle(self, images, inner, delay, 0, hang=False)

    def predict(self, images, ctxs=None):
        return self.predict_async(images, ctxs=ctxs).result()

    def __getattr__(self, name):
        # everything not fault-related (buckets, warmup, image_sizes, ...)
        # falls through so the wrapper is drop-in
        return getattr(self._engine, name)

    @classmethod
    def from_config(cls, engine, fc, **overrides):
        """Wrap per a config.FaultsConfig block; identity when disabled."""
        if not fc.enable:
            return engine
        kw = dict(
            seed=fc.seed,
            failure_rate=fc.failure_rate,
            fail_first_n=fc.fail_first_n,
            fail_at=fc.fail_at,
            latency_s=fc.latency_ms / 1e3,
            latency_rate=fc.latency_rate,
            latency_after_n=fc.latency_after_n,
            hang_at=fc.hang_at if fc.hang_at >= 0 else None,
        )
        kw.update(overrides)
        return cls(engine, **kw)
